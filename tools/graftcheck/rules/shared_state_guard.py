"""shared-state-guard: RacerD-style lockset race detection over the package.

A field that two thread roles can touch — the client thread appending to the
batcher queue while the batcher loop drains it, the poller swapping a version
while a batch snapshots it, N loadgen collectors bumping one counter — is
only safe under a *consistent, non-empty lockset*: every access holds the
same lock. A single unguarded read is enough for a torn snapshot or a lost
update, and no soak test reliably catches the interleaving; this rule
convicts it statically, per class, from the index's per-``self.X`` access
facts and the inferred thread topology (``tools/graftcheck/topology.py``).

Per class, each attribute accessed outside ``__init__`` must satisfy one of:

- **consistent lockset** — the intersection of locks held (lexically, or
  *definitely* held at every resolved call site reaching the method — the
  interprocedural lock context) across all accesses is non-empty;
- **immutable after publish** — written only in ``__init__`` (the ownership
  assumption: an object under construction is unpublished);
- **inherently safe** — the attribute is itself a ``Lock`` / ``Condition`` /
  ``Event`` / ``Queue`` / ``Thread``, or holds a project class instance
  (internally synchronized state is that class's own analysis problem —
  mutations through the reference are reads of the reference here);
- **single-writer annotation** — ``# graftcheck: owned-by=<role>`` on the
  field's definition line: only the named role writes, reads elsewhere
  accept benign staleness. The claim is *verified* — a write from any other
  role, or naming a multi-instance role (which races with itself), is an
  error;
- **ownership handoff** — the class (or a base) is marked
  ``# graftcheck: serialized``: instances cross threads only through a
  documented synchronization point that orders every access.

The race criterion needs concurrency evidence: accesses from ≥ 2 distinct
roles, or from one *multi* role (a pool / looped spawn shares state between
its own instances). Objects only the implicit ``main`` role touches are
assumed externally confined — flagging every single-threaded model's fields
would bury the real races.

Known blind spots (deliberate, documented in docs/static_analysis.md):
accesses through non-``self`` references (``req._state`` from the batcher),
module-level globals, and roles lost through callable-attribute indirection
(``self._execute = execute``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

from tools.graftcheck.engine import Finding, Project, Rule, register
from tools.graftcheck.rules.lock_order import _lock_id
from tools.graftcheck.topology import MAIN_ROLE, lock_context, topology_for

#: Builtin containers whose mutator-method calls are writes; a project-class
#: attribute's method calls are just reads of the reference.
BUILTIN_CONTAINERS = {
    "deque", "dict", "list", "set", "defaultdict", "OrderedDict", "Counter",
}


class AttrAccess:
    __slots__ = ("mode", "line", "locks", "regions", "node", "qual", "roles", "in_init")

    def __init__(self, mode, line, locks, regions, node, qual, roles, in_init):
        self.mode = mode  # "r" | "w" | "m"
        self.line = line
        self.locks = locks  # frozenset of canonical lock ids (lexical ∪ context)
        self.regions = regions  # raw lexical region ids ("self._lock@218")
        self.node = node
        self.qual = qual
        self.roles = roles
        self.in_init = in_init

    @property
    def is_write(self) -> bool:
        return self.mode in ("w", "m")


class ClassState:
    __slots__ = ("rel", "module", "cls", "cfacts", "attrs")

    def __init__(self, rel, module, cls, cfacts, attrs):
        self.rel = rel
        self.module = module
        self.cls = cls
        self.cfacts = cfacts
        self.attrs = attrs  # attr -> List[AttrAccess]


def _is_serialized(index, module: str, cname: str, seen: Optional[Set[str]] = None) -> bool:
    if seen is None:
        seen = set()
    if cname in seen:
        return False
    seen.add(cname)
    hit = index.resolve_class(cname, module)
    if hit is None:
        return False
    mod, cfacts = hit
    if "serialized" in cfacts.get("marks", []):
        return True
    return any(_is_serialized(index, mod, base, seen) for base in cfacts.get("bases", []))


def collect_class_states(project: Project) -> List[ClassState]:
    """Per-class shared-state accesses with effective locksets and thread
    roles — the shared substrate of shared-state-guard and check-then-act."""
    cached = getattr(project, "_class_states", None)
    if cached is not None:
        return cached
    index = project.index
    topo = topology_for(project)
    ctx = lock_context(index, _lock_id)

    states: List[ClassState] = []
    for rel in sorted(index.files):
        f = index.files[rel]
        module = f["module"]
        if not f["classes"]:
            continue
        by_cls: Dict[str, List[Tuple[str, Dict[str, Any]]]] = {}
        method_names: Dict[str, Set[str]] = {}
        for qual, ff in f["functions"].items():
            if not ff["cls"]:
                continue
            by_cls.setdefault(ff["cls"], []).append((qual, ff))
            if ff["parent"] is None:
                method_names.setdefault(ff["cls"], set()).add(ff["name"])
        for cname, cfacts in f["classes"].items():
            if _is_serialized(index, module, cname):
                continue
            safe = (
                set(cfacts["locks"])
                | set(cfacts["aliases"])
                | set(cfacts["event_attrs"])
                | set(cfacts["queue_attrs"])
                | set(cfacts["thread_attrs"])
            )
            methods = method_names.get(cname, set())
            attrs: Dict[str, List[AttrAccess]] = {}
            for qual, ff in by_cls.get(cname, []):
                node = f"{module}:{qual}"
                roles = frozenset(topo.roles_of(node))
                parts = qual.split(".")
                in_init = len(parts) > 1 and parts[1] == "__init__"
                fn_ctx = ctx.get(node, set())
                for attr, mode, line, held, regions in ff.get("attr_accesses", []):
                    if attr in safe or attr in methods:
                        continue
                    if mode == "m":
                        tname = cfacts["attr_types"].get(attr)
                        if tname and tname not in BUILTIN_CONTAINERS and index.resolve_class(tname, module):
                            mode = "r"  # project-class reference: internally synchronized
                    locks = frozenset(
                        {_lock_id(module, cname, tok) for tok in held} | fn_ctx
                    )
                    attrs.setdefault(attr, []).append(
                        AttrAccess(mode, line, locks, list(regions), node, qual, roles, in_init)
                    )
            if attrs:
                states.append(ClassState(rel, module, cname, cfacts, attrs))
    project._class_states = states
    return states


def shared_roles(topo, accesses: List[AttrAccess]) -> Optional[Set[str]]:
    """The role set making this attribute race-eligible, or None when the
    accesses lack concurrency evidence (single non-multi role)."""
    roles: Set[str] = set()
    for a in accesses:
        roles |= a.roles
    if len(roles) >= 2 or any(topo.is_multi(r) for r in roles):
        return roles
    return None


def _site(a: AttrAccess) -> str:
    verb = {"r": "read", "w": "written", "m": "mutated"}[a.mode]
    lock = f"holding {sorted(a.locks)[0]}" if a.locks else "with NO lock"
    return f"{verb} in {a.qual} (line {a.line}, {lock})"


@register
class SharedStateGuardRule(Rule):
    name = "shared-state-guard"
    severity = "error"
    description = (
        "every class attribute reachable from two thread roles (or one pool "
        "role) must have a consistent lockset, be immutable after __init__, "
        "be an inherently-safe primitive, or carry a verified "
        "`# graftcheck: owned-by=<role>` annotation"
    )

    def run(self, project: Project) -> List[Finding]:
        topo = topology_for(project)
        findings: List[Finding] = []
        for state in collect_class_states(project):
            marks = state.cfacts.get("attr_marks", {})
            for attr in sorted(state.attrs):
                accesses = [a for a in state.attrs[attr] if not a.in_init]
                if not accesses or not any(a.is_write for a in accesses):
                    continue  # immutable after publish (or never accessed live)
                roles = shared_roles(topo, accesses)
                if roles is None:
                    continue
                label = f"{state.cls}.{attr}"
                owner = marks.get(attr)
                if owner is not None:
                    findings.extend(
                        self._check_owned(state, label, owner, accesses, topo, roles)
                    )
                    continue
                common = frozenset.intersection(*(a.locks for a in accesses))
                if common:
                    continue
                findings.append(self._race_finding(state, label, accesses, topo, roles))
        return findings

    def _check_owned(self, state, label, owner, accesses, topo, roles) -> List[Finding]:
        out: List[Finding] = []
        if owner != MAIN_ROLE and owner not in topo.roles:
            first = min(accesses, key=lambda a: a.line)
            out.append(
                self.finding(
                    state.rel,
                    first.line,
                    f"{label} is annotated `owned-by={owner}` but no such thread "
                    f"role exists (inferred roles: "
                    f"{topo.describe(set(topo.roles) | {MAIN_ROLE})})",
                )
            )
            return out
        if topo.is_multi(owner):
            first = min(accesses, key=lambda a: a.line)
            out.append(
                self.finding(
                    state.rel,
                    first.line,
                    f"{label} is annotated `owned-by={owner}`, but {owner} is a "
                    "multi-instance role (pool/looped spawn) — its threads race "
                    "with each other, so single-writer ownership cannot hold",
                )
            )
            return out
        for a in accesses:
            if a.is_write and not (a.roles <= {owner}):
                out.append(
                    self.finding(
                        state.rel,
                        a.line,
                        f"{label} is annotated `owned-by={owner}` but is "
                        f"{_site(a)} on thread role(s) "
                        f"{topo.describe(a.roles - {owner})} — the single-writer "
                        "claim is violated; guard the field with a lock instead",
                    )
                )
        return out

    def _race_finding(self, state, label, accesses, topo, roles) -> Finding:
        # The most frequent lock across accesses (if any) is presumed the
        # intended guard; accesses missing it are the offenders we anchor on.
        freq: Dict[str, int] = {}
        for a in accesses:
            for lock in a.locks:
                freq[lock] = freq.get(lock, 0) + 1
        majority = max(freq, key=lambda k: (freq[k], k)) if freq else None
        if majority is not None:
            offenders = [a for a in accesses if majority not in a.locks]
            kind = f"inconsistent lockset (most accesses hold {majority})"
        else:
            offenders = [a for a in accesses if a.is_write] or accesses
            kind = "empty lockset"
        offenders.sort(key=lambda a: a.line)
        shown = "; ".join(_site(a) for a in offenders[:3])
        more = f" (+{len(offenders) - 3} more)" if len(offenders) > 3 else ""
        return self.finding(
            state.rel,
            offenders[0].line,
            f"data race candidate: {label} is shared across thread roles "
            f"[{topo.describe(roles)}] with {kind}: {shown}{more} — guard every "
            "access with one lock, make the field immutable after __init__, or "
            "annotate its definition with `# graftcheck: owned-by=<role>` if it "
            "is deliberately single-writer",
        )
