"""plan-key-completeness: every plan-affecting config read joins a rebuild key.

The PR 9/10 bug class, made a tier-1 gate: a config option read somewhere
under plan build changes what gets compiled, but if no rebuild key carries it,
flipping the option mid-process silently keeps serving the old plan. ROADMAP
item 2 (the precision tier) lands straight on top of this invariant.

The contract, checked whole-program over the v5 dataflow facts:

1. **Completeness** — every ``config.get(Options.X)`` site reachable through
   the resolved call graph from the plan-build surfaces (``PLAN_BUILD_ROOTS``)
   must name an option that is either *key-captured* (some read of it sits
   inside the transitive reach of a key-composition function) or declared
   plan-neutral in ``PLAN_NEUTRAL`` with a rationale. Anything else is an
   error at the offending read site — which is where ``--changed-only`` will
   anchor it, even when the digest lives in another file.
2. **Declaration honesty** — the declarative tables cannot rot silently:
   every ``PLAN_KEY_OPTIONS`` entry must actually be read within the capture
   reach of each key surface it claims, every ``PLAN_NEUTRAL`` entry must
   still be plan-reachable, and every named root/capture function must still
   exist in the index (a rename must not quietly disable the rule).

Key surfaces and their capture roots (a read is "captured by" a surface when
its function is reachable from one of these — their return values compose
into that surface's key):

- ``batch-fingerprint`` — ``PipelineModel._fingerprint`` (+ sparse hints);
  compared by ``_batch_plan`` before reusing a CompiledBatchPlan.
- ``serving-rebuild`` — the resolvers producing the keys ``_plan_for``
  compares (``resolve_plan_sharding`` / ``resolve_fusion_tier`` /
  ``resolve_sparse_hints``) plus ``ServingConfig.__init__`` which feeds them.
- ``plancache-digest`` — ``program_digest`` plus the same key resolvers; the
  digest additionally hashes the lowered StableHLO text, so trace-time
  constants are captured by construction (a blind spot this rule does not
  rely on — see docs/static_analysis.md).
"""
from __future__ import annotations

from typing import Dict, List, Set, Tuple

from tools.graftcheck.engine import Finding, Project, Rule, register

CONFIG_REL = "flink_ml_tpu/config.py"

def _option_keys(project: Project) -> Dict[str, Tuple[str, int]]:
    """Options attr -> (literal key, declaration line) from config.py facts."""
    facts = project.facts().get(CONFIG_REL)
    if not facts:
        return {}
    return {attr: (key, line) for attr, key, line in facts["config_options"]}


@register
class PlanKeyCompletenessRule(Rule):
    name = "plan-key-completeness"
    severity = "error"
    granularity = "project"
    cache_version = 2  # v2: TRAIN_NEUTRAL (train.mesh* asserted not plan-reachable)
    description = (
        "config reads reachable from plan build must be carried by the "
        "plancache digest, batch fingerprint and serving rebuild key"
    )

    #: Call-graph roots of plan build/compile — the surfaces the ISSUE contract
    #: names. Reads reachable from here decide what gets compiled.
    PLAN_BUILD_ROOTS = (
        "flink_ml_tpu.servable.planner:build_segments",
        "flink_ml_tpu.servable.planner:run_segment",
        "flink_ml_tpu.servable.plancache:program_digest",
        "flink_ml_tpu.builder.pipeline:PipelineModel._fingerprint",
        "flink_ml_tpu.builder.pipeline:PipelineModel._batch_plan",
        "flink_ml_tpu.builder.batch_plan:CompiledBatchPlan.build",
        "flink_ml_tpu.serving.server:InferenceServer._plan_for",
        "flink_ml_tpu.serving.plan:CompiledServingPlan.build",
        "flink_ml_tpu.servable.fusion:resolve_fusion_tier",
        "flink_ml_tpu.servable.precision:resolve_precision_tier",
    )

    #: Key-composition functions per rebuild-key surface: an option read inside
    #: the transitive reach of one of these is carried by that surface's key.
    KEY_CAPTURE_ROOTS: Dict[str, Tuple[str, ...]] = {
        "batch-fingerprint": (
            "flink_ml_tpu.builder.pipeline:PipelineModel._fingerprint",
            "flink_ml_tpu.servable.sparse:resolve_sparse_hints",
        ),
        "serving-rebuild": (
            "flink_ml_tpu.servable.sharding:resolve_plan_sharding",
            "flink_ml_tpu.servable.fusion:resolve_fusion_tier",
            "flink_ml_tpu.servable.precision:resolve_precision_tier",
            "flink_ml_tpu.servable.sparse:resolve_sparse_hints",
            "flink_ml_tpu.serving.server:ServingConfig.__init__",
        ),
        "plancache-digest": (
            "flink_ml_tpu.servable.plancache:program_digest",
            "flink_ml_tpu.servable.sharding:resolve_plan_sharding",
            "flink_ml_tpu.servable.fusion:resolve_fusion_tier",
            "flink_ml_tpu.servable.precision:resolve_precision_tier",
            "flink_ml_tpu.servable.sparse:resolve_sparse_hints",
        ),
    }

    #: Options asserted to be key-captured, with the surfaces that carry them.
    #: Direction 2 verifies each claim against the call graph every run.
    PLAN_KEY_OPTIONS: Dict[str, Tuple[str, ...]] = {
        "BATCH_MESH": ("batch-fingerprint",),
        "BATCH_MESH_MODEL": ("batch-fingerprint",),
        "SERVING_MESH": ("serving-rebuild",),
        "SERVING_MESH_MODEL": ("serving-rebuild",),
        "FUSION_MODE": ("batch-fingerprint", "serving-rebuild", "plancache-digest"),
        "FUSION_MEGAKERNEL": ("batch-fingerprint", "serving-rebuild", "plancache-digest"),
        "FUSION_MEGAKERNEL_MIN_SCORE": (
            "batch-fingerprint", "serving-rebuild", "plancache-digest",
        ),
        # Gates whether sparse hints exist at all; hints feed the sparse_key leg
        # of all three surfaces, so a flip rebuilds everywhere.
        "SPARSE_FASTPATH": (
            "batch-fingerprint", "serving-rebuild", "plancache-digest",
        ),
        # The precision tier (PR 19): the batch fingerprint reads it directly,
        # ServingConfig/resolve_precision_tier feed the server's rebuild
        # comparison, and program_digest appends the tier's cache_key leg.
        "PRECISION_MODE": (
            "batch-fingerprint", "serving-rebuild", "plancache-digest",
        ),
    }

    #: Options read under plan build that are genuinely plan-neutral — each entry
    #: carries its rationale and is itself checked (a stale entry is an error).
    PLAN_NEUTRAL: Dict[str, str] = {
        # Where compiled executables are persisted, never which program a key
        # maps to; the cache fails open and digests are content-addressed.
        "PLANCACHE_ENABLED": "cache placement only; digest identity is unaffected",
        "PLANCACHE_DIR": "cache placement only; digest identity is unaffected",
        "PLANCACHE_MAX_BYTES": "cache eviction budget only; never plan identity",
        # MeshContext defaults: plan paths always pass explicit axis sizes
        # resolved from the per-tier mesh options (batch.mesh / serving.mesh),
        # which ARE key-captured; the global axis options only seed training-side
        # mesh contexts constructed without arguments.
        "MESH_DATA_AXIS_SIZE": "default shadowed by key-captured per-tier mesh options",
        "MESH_MODEL_AXIS_SIZE": "default shadowed by key-captured per-tier mesh options",
    }

    #: Training-tier options asserted NEVER to be read under plan build — the
    #: inverse of PLAN_NEUTRAL (which allowlists *plan-reachable* reads, and
    #: whose rule 2b errors on entries nobody reads under plan build). These
    #: are checked the other way round: a read of one of these that becomes
    #: reachable from PLAN_BUILD_ROOTS is an error — at that point the option
    #: has started affecting compiled serving artifacts and must be
    #: key-captured (PLAN_KEY_OPTIONS) or justified in PLAN_NEUTRAL instead.
    TRAIN_NEUTRAL: Dict[str, str] = {
        # train.mesh* select the TRAINING mesh (parallel/train_sharding.py);
        # published servables are plain host arrays whatever mesh trained
        # them, so plan identity never depends on these. The sharded-vs-legacy
        # trainer split is carried by the model *fingerprint* tier instead
        # (KMeans.fit_stream stamps tier="deterministic") — a checkpoint
        # concern, not a plan-key concern.
        "TRAIN_MESH": "training topology only; servables are mesh-agnostic host arrays",
        "TRAIN_MESH_MODEL": "training topology only; servables are mesh-agnostic host arrays",
        "TRAIN_MESH_HOSTS": "jax.distributed bootstrap only; never plan identity",
    }

    def run(self, project: Project) -> List[Finding]:
        index = project.index
        findings: List[Finding] = []
        rel_of = {f["module"]: rel for rel, f in index.files.items()}
        decls = _option_keys(project)
        if not decls:
            return []  # not a tree with the config registry (fixture trees)

        def reads_in(roots) -> Dict[str, List[Tuple[str, int]]]:
            out: Dict[str, List[Tuple[str, int]]] = {}
            for node in index.reachable(list(roots), stop_marks=()):
                ff = index.function(node)
                if ff is None:
                    continue
                module = node.partition(":")[0]
                rel = rel_of.get(module, module)
                for attr, line in ff.get("config_reads", ()):
                    out.setdefault(attr, []).append((rel, line))
            return out

        # Roots that vanished (renamed/deleted) would silently disable the
        # gate — surface that loudly instead.
        for node in self.PLAN_BUILD_ROOTS + tuple(
            r for roots in self.KEY_CAPTURE_ROOTS.values() for r in roots
        ):
            if index.function(node) is None:
                findings.append(self.finding(
                    CONFIG_REL, 1,
                    f"plan-key surface {node} not found in the index — "
                    "update tools/graftcheck/rules/plan_key.py after the rename",
                ))

        plan_reads = reads_in(self.PLAN_BUILD_ROOTS)
        captured_by: Dict[str, Set[str]] = {}
        for surface, roots in self.KEY_CAPTURE_ROOTS.items():
            for attr in reads_in(roots):
                captured_by.setdefault(attr, set()).add(surface)

        # 1. completeness: plan-reachable read -> captured or declared neutral
        for attr, sites in sorted(plan_reads.items()):
            if attr in self.PLAN_NEUTRAL or attr in self.PLAN_KEY_OPTIONS or captured_by.get(attr):
                continue
            key = decls.get(attr, (attr, 0))[0]
            for rel, line in sites:
                findings.append(self.finding(
                    rel, line,
                    f"option {key!r} ({attr}) is read under plan build but "
                    "joins no rebuild key (plancache digest / batch "
                    "fingerprint / serving rebuild); add it to the key "
                    "composition or declare it in PLAN_NEUTRAL with a "
                    "rationale (rules/plan_key.py)",
                ))

        # 2a. every claimed (option, surface) pair must really be captured
        for attr, surfaces in sorted(self.PLAN_KEY_OPTIONS.items()):
            key, line = decls.get(attr, (attr, 1))
            for surface in surfaces:
                if surface not in captured_by.get(attr, set()):
                    findings.append(self.finding(
                        CONFIG_REL, line,
                        f"option {key!r} ({attr}) is declared plan-key for "
                        f"{surface} but no read of it is reachable from that "
                        "surface's key-composition functions",
                    ))

        # 2b. a neutral entry nobody reads under plan build is stale
        for attr, why in sorted(self.PLAN_NEUTRAL.items()):
            if attr not in plan_reads:
                key, line = decls.get(attr, (attr, 1))
                findings.append(self.finding(
                    CONFIG_REL, line,
                    f"PLAN_NEUTRAL entry {key!r} ({attr}) is no longer read "
                    "under plan build — remove the stale allowlist entry "
                    f"(rationale was: {why})",
                ))

        # 2c. TRAIN_NEUTRAL honesty, both directions: an entry that IS read
        # under plan build has outgrown its declaration; an entry whose option
        # no longer exists in the registry is stale.
        for attr, why in sorted(self.TRAIN_NEUTRAL.items()):
            if attr not in decls:
                findings.append(self.finding(
                    CONFIG_REL, 1,
                    f"TRAIN_NEUTRAL entry {attr} names no option in the config "
                    "registry — remove the stale entry "
                    f"(rationale was: {why})",
                ))
                continue
            for rel, line in plan_reads.get(attr, ()):
                key = decls[attr][0]
                findings.append(self.finding(
                    rel, line,
                    f"option {key!r} ({attr}) is declared train-only "
                    "(TRAIN_NEUTRAL) but is read under plan build here — "
                    "key-capture it (PLAN_KEY_OPTIONS) or justify it in "
                    "PLAN_NEUTRAL (rules/plan_key.py)",
                ))
        return findings
