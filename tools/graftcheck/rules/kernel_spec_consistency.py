"""kernel-spec-consistency: fused and per-stage math must be the same body.

The fast paths' bit-exactness contract (``docs/serving.md``,
``docs/batch_transform.md``) holds at the op level only because a stage's
``kernel_spec()`` composes the *same* ``ops/kernels.py`` ``*_fn`` body that
the stage's per-stage ``transform`` jits (via the matching ``*_kernel``
factory). A spec that hand-rolls its own jnp math can silently drift from the
fallback path — results then differ depending on which path a batch happens
to ride, the exact bug the shared-body pattern exists to prevent.

The check, per module that defines a ``kernel_spec`` method:

1. collect every name imported from ``flink_ml_tpu.ops.kernels`` and
   normalize it to its kernel *base* — strip a trailing ``_fn`` / ``_kernel``
   (``binarize_fn`` and ``binarize_kernel`` are one base, the documented
   pairing), then apply ``KERNEL_ALIASES`` for the historical pairs whose fn
   and factory names differ (``kmeans_predict_kernel`` jits
   ``kmeans_assign_fn``);
2. a ``kernel_spec`` body must reference at least one kernels import — a
   spec with none is doing its own math;
3. every base a ``kernel_spec`` body references must ALSO be referenced
   outside ``kernel_spec`` bodies in the same module (the transform path) —
   otherwise the fused path runs a body the per-stage path never does.

Heuristic by design (like jit-purity): references are tracked by name within
one module, so a spec built from helpers in another module is not followed.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from tools.graftcheck.engine import Finding, Project, Rule, SourceFile, register

KERNELS_MODULE = "flink_ml_tpu.ops.kernels"

#: fn-name base -> factory-name base for pairs that predate the *_fn/*_kernel
#: naming convention (the factory jits exactly that fn body).
KERNEL_ALIASES = {
    "kmeans_predict": "kmeans_assign",
    "logistic_predict": "logistic_from_dots",
    "dct_basis": "dct",  # the basis builder is part of the dct body pairing
}


def kernel_base(name: str) -> str:
    """Normalize an ops/kernels.py symbol to its body base."""
    for suffix in ("_kernel", "_fn"):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
            break
    return KERNEL_ALIASES.get(name, name)


def kernels_imports(tree: ast.AST) -> Dict[str, str]:
    """local bound name -> kernel base, for ``from flink_ml_tpu.ops.kernels
    import X [as Y]`` (and ``import flink_ml_tpu.ops.kernels as K`` attribute
    access is NOT tracked — the tree uses from-imports)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == KERNELS_MODULE:
            for alias in node.names:
                out[alias.asname or alias.name] = kernel_base(alias.name)
    return out


def _referenced_bases(node: ast.AST, bound: Dict[str, str]) -> Set[str]:
    return {
        bound[n.id]
        for n in ast.walk(node)
        if isinstance(n, ast.Name) and n.id in bound
    }


def _is_trivial(fn: ast.AST) -> bool:
    """A declaration-only kernel_spec: every return is a bare ``return`` /
    ``return None`` (the TransformerServable default hook, or an
    ineligible-params early-out-only stub). Such a def promises no fused
    math, so there is nothing to cross-check."""
    returns = [n for n in ast.walk(fn) if isinstance(n, ast.Return)]
    return all(
        r.value is None
        or (isinstance(r.value, ast.Constant) and r.value.value is None)
        for r in returns
    )


@register
class KernelSpecConsistencyRule(Rule):
    name = "kernel-spec-consistency"
    severity = "error"
    description = (
        "a kernel_spec must compose the same ops/kernels.py *_fn body its "
        "per-stage transform jits — no drift between fused and fallback math"
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for sf in project.iter_files("flink_ml_tpu/"):
            spec_defs = [
                node
                for node in ast.walk(sf.tree)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == "kernel_spec"
            ]
            if not spec_defs:
                continue
            bound = kernels_imports(sf.tree)
            spec_nodes = set()
            for fn in spec_defs:
                spec_nodes.update(ast.walk(fn))
            outside: Set[str] = {
                bound[n.id]
                for n in ast.walk(sf.tree)
                if isinstance(n, ast.Name) and n.id in bound and n not in spec_nodes
            }
            for fn in spec_defs:
                if _is_trivial(fn):
                    continue
                inside = _referenced_bases(fn, bound)
                if not inside:
                    findings.append(
                        self.finding(
                            sf.rel,
                            fn.lineno,
                            "kernel_spec references no ops/kernels.py body — "
                            "fused math must come from the shared *_fn bodies",
                        )
                    )
                    continue
                for base in sorted(inside - outside):
                    findings.append(
                        self.finding(
                            sf.rel,
                            fn.lineno,
                            f"kernel_spec composes {base!r} but the per-stage "
                            "transform path in this module never references "
                            f"a {base!r} kernel — fused and fallback math drift",
                        )
                    )
        return findings
