"""kernel-spec-consistency: fused and per-stage math must be the same body.

The fast paths' bit-exactness contract (``docs/serving.md``,
``docs/batch_transform.md``) holds at the op level only because a stage's
``kernel_spec()`` composes the *same* ``ops/kernels.py`` ``*_fn`` body that
the stage's per-stage ``transform`` jits (via the matching ``*_kernel``
factory). A spec that hand-rolls its own jnp math can silently drift from the
fallback path — results then differ depending on which path a batch happens
to ride, the exact bug the shared-body pattern exists to prevent.

Since graftcheck v2 the per-module analysis comes from the shared index's
kernel facts (``facts["kernels"]``): the bound → base import map of
``flink_ml_tpu.ops.kernels`` names (``binarize_fn`` / ``binarize_kernel``
normalize to one base; ``KERNEL_ALIASES`` pairs the historical fn/factory
names), the bases referenced inside each ``kernel_spec`` body, and the bases
referenced outside them (the transform path). The check, per module that
defines a ``kernel_spec`` method:

1. a non-trivial ``kernel_spec`` body must reference at least one kernels
   import — a spec with none is doing its own math;
2. every base a ``kernel_spec`` body references must ALSO be referenced
   outside ``kernel_spec`` bodies in the same module (the transform path) —
   otherwise the fused path runs a body the per-stage path never does.

Heuristic by design (like jit-purity): references are tracked by name within
one module, so a spec built from helpers in another module is not followed.
"""
from __future__ import annotations

import ast
from typing import Dict, List

from tools.graftcheck.engine import Finding, Project, Rule, SourceFile, register
from tools.graftcheck.index import KERNEL_ALIASES, KERNELS_MODULE, kernel_base  # noqa: F401  (re-export: the historical home of these names)


def kernels_imports(tree: ast.AST) -> Dict[str, str]:
    """local bound name -> kernel base, for ``from flink_ml_tpu.ops.kernels
    import X [as Y]`` — retained for shims/tests that analyze a lone AST."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == KERNELS_MODULE:
            for alias in node.names:
                out[alias.asname or alias.name] = kernel_base(alias.name)
    return out


@register
class KernelSpecConsistencyRule(Rule):
    name = "kernel-spec-consistency"
    severity = "error"
    granularity = "file"
    cache_version = 2  # v2: migrated onto the shared index facts
    description = (
        "a kernel_spec must compose the same ops/kernels.py *_fn body its "
        "per-stage transform jits — no drift between fused and fallback math"
    )

    def check_file(self, project: Project, sf: SourceFile) -> List[Finding]:
        if not sf.rel.startswith("flink_ml_tpu/"):
            return []
        facts = project.facts().get(sf.rel)
        if not facts:
            return []
        kf = facts["kernels"]
        findings: List[Finding] = []
        outside = set(kf["outside"])
        for spec in kf["specs"]:
            if spec["trivial"]:
                continue
            inside = set(spec["inside"])
            if not inside:
                findings.append(
                    self.finding(
                        sf.rel,
                        spec["line"],
                        "kernel_spec references no ops/kernels.py body — "
                        "fused math must come from the shared *_fn bodies",
                    )
                )
                continue
            for base in sorted(inside - outside):
                findings.append(
                    self.finding(
                        sf.rel,
                        spec["line"],
                        f"kernel_spec composes {base!r} but the per-stage "
                        "transform path in this module never references "
                        f"a {base!r} kernel — fused and fallback math drift",
                    )
                )
        return findings
