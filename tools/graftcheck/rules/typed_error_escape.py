"""typed-error-escape: the serving/fleet request surfaces fail typed, always.

chaos_smoke and fleet_smoke prove the "untyped-error bin empty" contract on
the paths they happen to exercise; this rule generalizes it statically to
every path: an interprocedural exception escape analysis over the resolved
call graph, proving each ``raise`` reachable from a request surface resolves
to a typed ``ServingError`` subclass or a documented system exception.

Mechanics (v5 facts): every raise site carries its resolved class name and
the lexically enclosing catcher names; every call site carries the catcher
names guarding it. Escapes propagate by fixpoint — a function's escape set is
its own uncaught raises plus each callee's escapes that survive the call
site's guards — with subclass-aware catching (``except ServingError`` catches
``ServingOverloadedError``; a handler that only re-raises is transparent and
never swallows, see index._handler_reraises). Each escaping class keeps one
witness raise site for anchoring, so ``--changed-only`` lands on the raise
that needs wrapping, not on the surface.

Allowed escapes:

- ``ServingError`` and subclasses (resolved transitively via class bases) —
  the typed contract of docs/serving.md.
- ``InjectedFault`` — chaos-armed test faults, counted in their own loadgen
  bin by design.
- ``DOCUMENTED_SYSTEM`` — argument-contract violations raised synchronously
  at the call boundary (caller bugs, not runtime failures), documented in
  docs/serving.md's error-contract table.
- ``RAISE_FACTORIES`` — functions whose return value is raised and is
  guaranteed typed (e.g. ``decode_error`` reconstructs the typed class
  carried over the replica wire protocol).

Blind spots (docs/static_analysis.md): raises stored on an object and
re-raised across a thread rendezvous (``req.error`` → ``Request.result``) are
invisible to the lexical call graph — the batcher wraps those typed at the
single ``_deliver_error`` seam, and the runtime smokes cover the handoff.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from tools.graftcheck.engine import Finding, Project, Rule, register

#: Typed contract roots: anything whose ancestry reaches one of these names
#: is an allowed escape.
TYPED_BASES = {"ServingError", "InjectedFault"}

#: Documented system exceptions: synchronous argument-contract violations —
#: see the error-contract table in docs/serving.md.
DOCUMENTED_SYSTEM = {"ValueError", "TypeError", "IndexError"}

#: Functions whose *return value* is raised and guaranteed typed.
RAISE_FACTORIES = {"decode_error"}

#: Builtin exception hierarchy (the slice this tree raises/catches).
_BUILTIN_BASES: Dict[str, str] = {
    "ServingDeadlineError": "TimeoutError",  # also ServingError via class_table
    "TimeoutError": "OSError",
    "FileNotFoundError": "OSError",
    "FileExistsError": "OSError",
    "ConnectionError": "OSError",
    "BrokenPipeError": "ConnectionError",
    "ConnectionResetError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "OSError": "Exception",
    "IOError": "OSError",
    "IndexError": "LookupError",
    "KeyError": "LookupError",
    "LookupError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "ArithmeticError": "Exception",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "RuntimeError": "Exception",
    "ValueError": "Exception",
    "TypeError": "Exception",
    "AttributeError": "Exception",
    "ImportError": "Exception",
    "StopIteration": "Exception",
    "AssertionError": "Exception",
    "Exception": "BaseException",
    "SystemExit": "BaseException",
    "KeyboardInterrupt": "BaseException",
}

_CATCH_ALL = {"*", "BaseException", "Exception"}


def _ancestors(index, name: str) -> Set[str]:
    """All ancestor class names of ``name`` (project classes + builtins)."""
    out: Set[str] = set()
    work = [name]
    while work:
        cur = work.pop()
        if cur in out:
            continue
        out.add(cur)
        hit = index.resolve_class(cur)
        if hit is not None:
            work.extend(hit[1].get("bases", ()))
        if cur in _BUILTIN_BASES:
            work.append(_BUILTIN_BASES[cur])
    return out


def _caught(index, cls: Optional[str], guards) -> bool:
    """Would a raise of ``cls`` be swallowed by these lexical catchers?
    Unknown classes are only caught by catch-alls (err toward reporting)."""
    if not guards:
        return False
    gset = set(guards)
    if gset & _CATCH_ALL:
        return True
    if cls is None:
        return False
    return bool(_ancestors(index, cls) & gset)


@register
class TypedErrorEscapeRule(Rule):
    name = "typed-error-escape"
    severity = "error"
    granularity = "project"
    cache_version = 1
    description = (
        "every raise reachable from the serving/fleet request surfaces must "
        "resolve to a typed ServingError subclass or a documented exception"
    )

    #: Client-facing request surfaces: submit/predict entries, the result
    #: rendezvous objects, the fleet router and retrieval client.
    REQUEST_SURFACES = (
        "flink_ml_tpu.serving.server:InferenceServer.submit",
        "flink_ml_tpu.serving.server:InferenceServer.predict",
        "flink_ml_tpu.serving.batcher:MicroBatcher.submit",
        "flink_ml_tpu.serving.batcher:PendingRequest.result",
        "flink_ml_tpu.fleet.router:FleetRouter.submit",
        "flink_ml_tpu.fleet.router:FleetRouter.predict",
        "flink_ml_tpu.fleet.router:_FleetHandle.result",
        "flink_ml_tpu.fleet.router:_FailedPending.result",
        "flink_ml_tpu.retrieval.client:RetrievalClient.query",
    )

    #: Background thread entries: an untyped raise escaping one of these kills
    #: the loop thread instead of failing one request — same contract, worse
    #: blast radius. Deliberately NOT every hot-root-marked function: dispatch
    #: seams like CompiledServingPlan.dispatch raise typed control-flow
    #: exceptions (IneligibleBatch) their direct caller handles; only the
    #: outermost thread targets belong here.
    BACKGROUND_SURFACES = (
        "flink_ml_tpu.serving.batcher:MicroBatcher._loop",
    )

    #: Raise sites allowlisted by (witness file, class): statically-verified
    #: invariant violations that cannot fire on a clean tree. Each entry carries
    #: the proof obligation that replaces wrapping.
    SITE_ALLOWLIST: Dict[Tuple[str, str], str] = {
        # trip()/arm() on an unregistered fault-point name. Dead by
        # construction: the fault-points rule (error severity, tier-1 gated)
        # proves every trip/arm site names a registered point, and the tests
        # pin LookupError as the registry's misuse contract.
        ("flink_ml_tpu/faults.py", "LookupError"):
            "fault-point registry misuse, statically proven unreachable",
    }

    #: Thread-rendezvous seams: functions that re-raise an error object carried
    #: across the batcher/router thread boundary (``raise self.error``). The
    #: lexical call graph cannot see what was stored, so their *dynamic* raises
    #: are excused here — the runtime guarantee lives at the single fill seams
    #: (``MicroBatcher._deliver_error`` wraps non-typed errors in
    #: ``ServingExecutionError``; ``_FailedPending`` is filled only from an
    #: ``except ServingError`` handler) and is regression-tested in
    #: tests/test_serving_errors.py.
    RENDEZVOUS_SEAMS = {
        "flink_ml_tpu.serving.batcher:PendingRequest.result",
        "flink_ml_tpu.fleet.router:_FailedPending.result",
    }

    def run(self, project: Project) -> List[Finding]:
        index = project.index
        roots = [
            r for r in self.REQUEST_SURFACES + self.BACKGROUND_SURFACES
            if index.function(r) is not None
        ]
        if not roots:
            return []  # fixture tree without serving surfaces

        # escapes[node]: class name (or witness key for unresolved raises)
        #   -> (witness rel, line, display name)
        escapes: Dict[str, Dict[str, Tuple[str, int, str]]] = {}
        guarded_edges: Dict[str, List[Tuple[str, List[str]]]] = {}
        for rel in sorted(index.files):
            f = index.files[rel]
            module = f["module"]
            for qual, ff in f["functions"].items():
                node = f"{module}:{qual}"
                mine: Dict[str, Tuple[str, int, str]] = {}
                for cls, line, guards, detail in ff.get("raises", ()):
                    if _caught(index, cls, guards):
                        continue
                    if node in self.RENDEZVOUS_SEAMS and cls is None:
                        continue  # thread-rendezvous re-raise, see above
                    if cls is None:
                        shown = detail or "dynamic raise"
                        mine.setdefault(f"?{rel}:{line}", (rel, line, shown))
                    else:
                        mine.setdefault(cls, (rel, line, cls))
                if mine:
                    escapes[node] = mine
                edges: List[Tuple[str, List[str]]] = []
                for ref, line, _held, guards in ff.get("calls", ()):
                    tgt = index.resolve_ref(module, ff["cls"], qual, ref)
                    if tgt is not None:
                        edges.append((tgt, guards))
                if edges:
                    guarded_edges[node] = edges

        changed = True
        while changed:
            changed = False
            for node, edges in guarded_edges.items():
                mine = escapes.setdefault(node, {})
                for tgt, guards in edges:
                    for key, witness in escapes.get(tgt, {}).items():
                        if key in mine:
                            continue
                        cls = None if key.startswith("?") else key
                        if _caught(index, cls, guards):
                            continue
                        mine[key] = witness
                        changed = True

        findings: List[Finding] = []
        reported: Dict[Tuple[str, int, str], Set[str]] = {}
        for root in roots:
            for key, (rel, line, shown) in escapes.get(root, {}).items():
                cls = None if key.startswith("?") else key
                if cls is not None:
                    if cls in RAISE_FACTORIES:
                        continue
                    if (rel, cls) in self.SITE_ALLOWLIST:
                        continue
                    anc = _ancestors(index, cls)
                    if anc & TYPED_BASES:
                        continue
                    # a documented ancestor covers subclasses (OffLadderError
                    # is a ValueError: same argument-contract bucket)
                    if anc & DOCUMENTED_SYSTEM:
                        continue
                reported.setdefault((rel, line, shown), set()).add(root)
        for (rel, line, shown), surfaces in sorted(reported.items()):
            names = ", ".join(sorted(s.split(":")[-1] for s in surfaces))
            findings.append(self.finding(
                rel, line,
                f"raise of {shown} can escape untyped to request surface(s) "
                f"{names}; wrap it in a ServingError subclass or catch it on "
                "the way out (typed-error contract, docs/serving.md)",
            ))
        return findings
