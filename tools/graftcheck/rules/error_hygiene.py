"""error-hygiene: no silently swallowed exceptions in library code.

A bare ``except:`` catches ``KeyboardInterrupt``/``SystemExit`` and hides the
stack trace of every bug below it; ``except Exception: pass`` is the same
silence with better manners. In a fleet-scale runtime the symptom is goodput
that degrades with no diagnostic — a poller that stops polling, a cache that
stops spilling — so library code must either catch something *narrow* or
*do* something (log, count a metric, re-raise) with what it caught.

Flagged under the analyzed tree:

- any bare ``except:``;
- ``except Exception:`` / ``except BaseException:`` (alone or in a tuple)
  whose body is only ``pass`` / ``...``.

Exempt: handlers inside ``__del__`` — a finalizer that raises during
interpreter teardown is strictly worse than one that swallows.
"""
from __future__ import annotations

import ast
from typing import List

from tools.graftcheck.engine import Finding, Project, Rule, register

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node: ast.AST | None) -> bool:
    if type_node is None:
        return True
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Attribute):
        return type_node.attr in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    return False


def _is_silent(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring or `...`
        return False
    return True


@register
class ErrorHygieneRule(Rule):
    name = "error-hygiene"
    severity = "error"
    granularity = "file"
    cache_version = 2  # v2: file-granularity (findings cached per content hash)
    description = (
        "no bare `except:`; no `except Exception: pass` outside finalizers — "
        "catch narrowly or handle (log/count/re-raise)"
    )

    def check_file(self, project: Project, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        if sf.tree is None:
            return findings  # parse error reported by the engine
        func_stack: List[str] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_stack.append(node.name)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                func_stack.pop()
                return
            if isinstance(node, ast.ExceptHandler) and "__del__" not in func_stack:
                if node.type is None:
                    findings.append(
                        self.finding(
                            sf.rel,
                            node.lineno,
                            "bare `except:` catches KeyboardInterrupt/SystemExit "
                            "— name the exception(s)",
                        )
                    )
                elif _is_broad(node.type) and _is_silent(node.body):
                    findings.append(
                        self.finding(
                            sf.rel,
                            node.lineno,
                            "`except Exception: pass` silently swallows every "
                            "error — catch narrowly, or log/count the failure",
                        )
                    )
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(sf.tree)
        return findings
