"""Rule modules. Importing this package registers every rule with the engine
(``tools.graftcheck.engine.REGISTRY``); a new rule = a new module here plus an
import line below. See docs/static_analysis.md for the authoring walkthrough.
"""
from tools.graftcheck.rules import (  # noqa: F401  (imported for registration)
    blocking_under_lock,
    check_then_act,
    elementwise_claim,
    error_hygiene,
    fault_points,
    fusion_tier,
    host_sync,
    jit_purity,
    kernel_spec_consistency,
    layer_deps,
    lock_order,
    plan_key,
    recompile_hazard,
    registry_consistency,
    shared_state_guard,
    typed_error_escape,
)
