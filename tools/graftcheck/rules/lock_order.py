"""lock-order: the lock-acquisition graph of the serving tier must be acyclic.

The serving runtime holds real locks on real request paths — the batcher's
queue lock, the registry's swap lock, the server's template lock, and the two
metrics locks every one of them calls into. A cycle in the "acquired while
holding" relation is a deadlock waiting for the right interleaving, and no
test reliably catches it: this rule derives the graph statically and fails on
any cycle (including self-loops — ``threading.Lock`` is non-reentrant).

How the graph is built (scope: ``flink_ml_tpu/serving/`` + ``metrics.py``):

1. **Lock nodes** — every ``self.X = threading.Lock()`` / ``RLock()`` /
   ``Condition()`` in a class body becomes node ``<module>.<Class>.X``;
   ``threading.Condition(self.Y)`` makes ``X`` an *alias* of ``Y`` (entering
   the condition acquires that lock).
2. **Direct edges** — ``with self.A:`` lexically nested inside
   ``with self.B:`` adds ``B -> A``.
3. **Call edges** — a call made while holding ``B`` adds ``B -> L`` for every
   lock ``L`` the callee may (transitively) acquire. Callees resolve through
   ``self.method(...)``, ``self.attr.method(...)`` where ``attr`` was
   constructed (or annotated) as an analyzed class, module-level singletons
   (``metrics = MetricsRegistry()``), and ``ClassName(...)`` constructors.

Known blind spots, chosen to keep the rule sound-for-this-codebase rather
than universally complete: nested ``def``s are deferred work (analyzed at
their own call sites, not where defined), property reads are not calls, and
an unresolvable callee contributes no edge.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.graftcheck.engine import Finding, Project, Rule, SourceFile, register

SCOPE = ("flink_ml_tpu/serving/", "flink_ml_tpu/metrics.py")

_LOCK_CTORS = {"Lock", "RLock"}


@dataclass
class _Method:
    cls: "_Class"
    node: ast.FunctionDef
    acquires: Set[str] = field(default_factory=set)  # canonical lock ids, direct
    calls: Set[Tuple[str, str]] = field(default_factory=set)  # (class qualname, method)
    held_calls: Set[Tuple[str, Tuple[str, str]]] = field(default_factory=set)
    nest_edges: Set[Tuple[str, str, int]] = field(default_factory=set)  # (outer, inner, line)
    held_call_lines: Dict[Tuple[str, Tuple[str, str]], int] = field(default_factory=dict)


@dataclass
class _Class:
    qualname: str  # "<module>.<Class>"
    name: str
    sf: SourceFile
    node: ast.ClassDef
    locks: Dict[str, int] = field(default_factory=dict)  # attr -> def line
    aliases: Dict[str, str] = field(default_factory=dict)  # attr -> lock attr
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> class simple name
    methods: Dict[str, _Method] = field(default_factory=dict)

    def lock_id(self, attr: str) -> Optional[str]:
        attr = self.aliases.get(attr, attr)
        if attr in self.locks:
            return f"{self.qualname}.{attr}"
        return None


@dataclass
class LockGraph:
    nodes: Dict[str, Tuple[str, int]]  # lock id -> (rel path, def line)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]]  # (a, b) -> (path, line, why)

    def cycles(self) -> List[List[str]]:
        """Elementary cycles, each reported once (rotated to min node)."""
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        seen: Set[Tuple[str, ...]] = set()
        out: List[List[str]] = []

        def dfs(start: str, node: str, path: List[str], visited: Set[str]) -> None:
            for nxt in adj.get(node, []):
                if nxt == start:
                    i = path.index(min(path))
                    canon = tuple(path[i:] + path[:i])
                    if canon not in seen:
                        seen.add(canon)
                        out.append(list(canon))
                elif nxt not in visited and nxt >= start:
                    visited.add(nxt)
                    dfs(start, nxt, path + [nxt], visited)
                    visited.discard(nxt)

        for n in sorted(self.nodes):
            dfs(n, n, [n], {n})
        return out


def _ctor_class_name(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _annotation_name(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.strip("\"'")
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def build_lock_graph(project: Project, scope: Sequence[str] = SCOPE) -> LockGraph:
    files = [sf for sf in project.files if any(sf.rel.startswith(p) for p in scope)]

    # Pass 1: classes, locks/aliases, attribute types, module singletons.
    classes: Dict[str, _Class] = {}  # simple name -> info (corpus-wide)
    singletons: Dict[str, str] = {}  # bare name -> class simple name
    for sf in files:
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                cname = _ctor_class_name(node.value)
                if cname:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            singletons[tgt.id] = cname
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                classes[node.name] = _Class(
                    qualname=f"{sf.module}.{node.name}", name=node.name, sf=sf, node=node
                )
    for ci in classes.values():
        for item in ci.node.body:
            if isinstance(item, ast.FunctionDef):
                ci.methods[item.name] = _Method(cls=ci, node=item)
                ann = {
                    a.arg: _annotation_name(a.annotation)
                    for a in item.args.args + item.args.kwonlyargs
                }
                for sub in ast.walk(item):
                    if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                        continue
                    attr = _self_attr(sub.targets[0])
                    if attr is None:
                        continue
                    val = sub.value
                    if isinstance(val, ast.Call) and isinstance(val.func, ast.Attribute):
                        # threading.Lock() / RLock() / Condition(...)
                        if val.func.attr in _LOCK_CTORS:
                            ci.locks[attr] = sub.lineno
                        elif val.func.attr == "Condition":
                            inner = _self_attr(val.args[0]) if val.args else None
                            if inner is not None:
                                ci.aliases[attr] = inner
                            else:
                                ci.locks[attr] = sub.lineno  # owns its lock
                    elif isinstance(val, ast.Call):
                        cname = _ctor_class_name(val)
                        if cname in classes:
                            ci.attr_types[attr] = cname
                    elif isinstance(val, ast.Name) and ann.get(val.id) in classes:
                        ci.attr_types[attr] = ann[val.id]

    # Pass 2: per-method acquisition/call structure (nested defs excluded —
    # a closure's body runs when called, not where written).
    def resolve_call(ci: _Class, call: ast.Call) -> Optional[Tuple[str, str]]:
        func = call.func
        if isinstance(func, ast.Attribute):
            recv = func.value
            if isinstance(recv, ast.Name):
                if recv.id == "self" and func.attr in ci.methods:
                    return (ci.qualname, func.attr)
                tname = singletons.get(recv.id)
                if tname in classes and func.attr in classes[tname].methods:
                    return (classes[tname].qualname, func.attr)
            attr = _self_attr(recv)
            if attr is not None:
                tname = ci.attr_types.get(attr)
                if tname in classes and func.attr in classes[tname].methods:
                    return (classes[tname].qualname, func.attr)
        elif isinstance(func, ast.Name) and func.id in classes:
            if "__init__" in classes[func.id].methods:
                return (classes[func.id].qualname, "__init__")
        return None

    by_qualname = {ci.qualname: ci for ci in classes.values()}

    def analyze(mi: _Method) -> None:
        ci = mi.cls

        def walk(node: ast.AST, held: List[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return
            if isinstance(node, ast.With):
                acquired_here: List[str] = []
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    lock = ci.lock_id(attr) if attr else None
                    if lock:
                        mi.acquires.add(lock)
                        for h in held:
                            mi.nest_edges.add((h, lock, node.lineno))
                        acquired_here.append(lock)
                    else:
                        walk(item.context_expr, held)
                for stmt in node.body:
                    walk(stmt, held + acquired_here)
                return
            if isinstance(node, ast.Call):
                callee = resolve_call(ci, node)
                if callee is not None:
                    mi.calls.add(callee)
                    for h in held:
                        mi.held_calls.add((h, callee))
                        mi.held_call_lines.setdefault((h, callee), node.lineno)
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in mi.node.body:
            walk(stmt, [])

    for ci in classes.values():
        for mi in ci.methods.values():
            analyze(mi)

    # Fixpoint: locks a method may acquire transitively through its calls.
    direct: Dict[Tuple[str, str], Set[str]] = {
        (ci.qualname, m): set(mi.acquires)
        for ci in classes.values()
        for m, mi in ci.methods.items()
    }
    trans: Dict[Tuple[str, str], Set[str]] = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for ci in classes.values():
            for m, mi in ci.methods.items():
                mine = trans[(ci.qualname, m)]
                before = len(mine)
                for callee in mi.calls:
                    mine |= trans.get(callee, set())
                if len(mine) != before:
                    changed = True

    nodes: Dict[str, Tuple[str, int]] = {}
    for ci in classes.values():
        for attr, line in ci.locks.items():
            nodes[f"{ci.qualname}.{attr}"] = (ci.sf.rel, line)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for ci in classes.values():
        for m, mi in ci.methods.items():
            where = f"{ci.qualname}.{m}"
            for outer, inner, line in mi.nest_edges:
                edges.setdefault(
                    (outer, inner), (ci.sf.rel, line, f"nested `with` in {where}")
                )
            for (held, callee), line in mi.held_call_lines.items():
                for lock in trans.get(callee, set()):
                    if lock == held and lock not in direct.get(callee, set()):
                        # Re-acquisition of the held lock deep in the call
                        # chain is a *consequence* of a cycle among the other
                        # edges, which will be reported on its own — a derived
                        # self-loop here would triple-report one deadlock.
                        continue
                    edges.setdefault(
                        (held, lock),
                        (
                            ci.sf.rel,
                            line,
                            f"{where} calls {callee[0]}.{callee[1]} while holding",
                        ),
                    )
    return LockGraph(nodes=nodes, edges=edges)


@register
class LockOrderRule(Rule):
    name = "lock-order"
    severity = "error"
    description = (
        "the serving-tier lock-acquisition graph (with-nesting + cross-module "
        "call edges) must be acyclic"
    )

    def run(self, project: Project) -> List[Finding]:
        graph = build_lock_graph(project)
        findings: List[Finding] = []
        for cycle in graph.cycles():
            ring = cycle + [cycle[0]]
            first_edge = graph.edges[(ring[0], ring[1])]
            detail = "; ".join(
                f"{a} -> {b} ({graph.edges[(a, b)][2]} at {graph.edges[(a, b)][0]}:{graph.edges[(a, b)][1]})"
                for a, b in zip(ring, ring[1:])
            )
            findings.append(
                self.finding(
                    first_edge[0],
                    first_edge[1],
                    f"lock-order cycle: {' -> '.join(ring)} — {detail}",
                )
            )
        return findings
