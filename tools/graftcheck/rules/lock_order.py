"""lock-order: the whole-program lock-acquisition graph must be acyclic.

The runtime holds real locks on real request paths — the batcher's queue
lock, the registry's swap lock, the adaptive controller and its goodput
ledger, the loadgen step counters, the trace ring, the config/faults/metrics
registries, and the module-level mesh/native/readback-pool locks. A cycle in
the "acquired while holding" relation is a deadlock waiting for the right
interleaving, and no test reliably catches it: this rule derives the graph
statically and fails on any cycle (including self-loops —
``threading.Lock`` is non-reentrant).

Until graftcheck v3 the graph was hand-scoped to ``serving/`` +
``metrics.py`` (5 nodes); the inferred thread topology
(``tools/graftcheck/topology.py``) made whole-program scoping the default:
every lock any thread role can reach joins the acyclicity contract, and the
historical serving graph is asserted (in tests) to be a subgraph of this
one.

Since graftcheck v2 the rule is a thin composition over the **shared project
index** (``tools/graftcheck/index.py``): lock nodes, ``with``-nesting edges
and calls-made-while-holding all come from the per-file facts the index
extracts once for every rule, and callee resolution (``self.method``, typed
attributes, module singletons like ``metrics``, imported functions,
constructors) is the index's call graph. The graph composition is:

1. **Lock nodes** — every ``self.X = threading.Lock()`` / ``RLock()`` /
   ``Condition()`` in a scoped class becomes node ``<module>.<Class>.X``
   (``threading.Condition(self.Y)`` aliases ``Y``); module-level locks become
   ``<module>.<NAME>``.
2. **Direct edges** — ``with self.A:`` lexically nested inside
   ``with self.B:`` adds ``B -> A``.
3. **Call edges** — a call made while holding ``B`` adds ``B -> L`` for every
   lock ``L`` the resolved callee may (transitively) acquire.

Known blind spots, chosen to keep the rule sound-for-this-codebase rather
than universally complete: nested ``def``s are analyzed at their own call
sites (not where defined), property reads are not calls, and an unresolvable
callee contributes no edge.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.graftcheck.engine import Finding, Project, Rule, register


@dataclass
class LockGraph:
    nodes: Dict[str, Tuple[str, int]]  # lock id -> (rel path, def line)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]]  # (a, b) -> (path, line, why)

    def cycles(self) -> List[List[str]]:
        """Elementary cycles, each reported once (rotated to min node)."""
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        seen: Set[Tuple[str, ...]] = set()
        out: List[List[str]] = []

        def dfs(start: str, node: str, path: List[str], visited: Set[str]) -> None:
            for nxt in adj.get(node, []):
                if nxt == start:
                    i = path.index(min(path))
                    canon = tuple(path[i:] + path[:i])
                    if canon not in seen:
                        seen.add(canon)
                        out.append(list(canon))
                elif nxt not in visited and nxt >= start:
                    visited.add(nxt)
                    dfs(start, nxt, path + [nxt], visited)
                    visited.discard(nxt)

        for n in sorted(self.nodes):
            dfs(n, n, [n], {n})
        return out


def _lock_id(module: str, cls, token: str) -> str:
    """Canonical lock id for a facts token: ``self.<attr>`` on a class lock,
    ``mod.<NAME>`` on a module-level lock."""
    if token.startswith("self."):
        return f"{module}.{cls}.{token[len('self.'):]}"
    return f"{module}.{token[len('mod.'):]}"


def build_lock_graph(project: Project, scope: Optional[Sequence[str]] = None) -> LockGraph:
    """The whole-program lock graph (``scope`` narrows to path prefixes for
    targeted analysis; the rule itself always runs unscoped)."""
    index = project.index
    in_scope = [
        rel
        for rel in sorted(index.files)
        if scope is None or any(rel.startswith(p) for p in scope)
    ]

    nodes: Dict[str, Tuple[str, int]] = {}
    for rel in in_scope:
        f = index.files[rel]
        module = f["module"]
        for cname, cfacts in f["classes"].items():
            for attr, line in cfacts["locks"].items():
                nodes[f"{module}.{cname}.{attr}"] = (rel, line)
        for name, line in f["module_locks"].items():
            nodes[f"{module}.{name}"] = (rel, line)

    # Direct acquisition per call-graph node, then the transitive fixpoint
    # over the resolved call graph ("which locks might this callee take").
    direct: Dict[str, Set[str]] = {}
    for rel in in_scope:
        f = index.files[rel]
        module = f["module"]
        for qual, ff in f["functions"].items():
            acquired = {
                _lock_id(module, ff["cls"], tok) for tok in ff["acquires"]
            }
            if acquired:
                direct[f"{module}:{qual}"] = acquired
    trans = index.transitive_closure(direct)

    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for rel in in_scope:
        f = index.files[rel]
        module = f["module"]
        for qual in sorted(f["functions"]):
            ff = f["functions"][qual]
            where = f"{module}.{qual}"
            for outer, inner, line in ff["nest_edges"]:
                a = _lock_id(module, ff["cls"], outer)
                b = _lock_id(module, ff["cls"], inner)
                edges.setdefault((a, b), (rel, line, f"nested `with` in {where}"))
            seen_calls: Set[Tuple[str, str]] = set()
            for ref, line, held, _guards in ff["calls"]:
                if not held:
                    continue
                callee = index.resolve_ref(module, ff["cls"], qual, ref)
                if callee is None:
                    continue
                callee_display = callee.replace(":", ".")
                for tok in held:
                    held_id = _lock_id(module, ff["cls"], tok)
                    if (held_id, callee) in seen_calls:
                        continue
                    seen_calls.add((held_id, callee))
                    for lock in trans.get(callee, set()):
                        if lock == held_id and lock not in direct.get(callee, set()):
                            # Re-acquisition of the held lock deep in the call
                            # chain is a *consequence* of a cycle among the
                            # other edges, which will be reported on its own —
                            # a derived self-loop here would triple-report one
                            # deadlock.
                            continue
                        edges.setdefault(
                            (held_id, lock),
                            (
                                rel,
                                line,
                                f"{where} calls {callee_display} while holding",
                            ),
                        )
    return LockGraph(nodes=nodes, edges=edges)


@register
class LockOrderRule(Rule):
    name = "lock-order"
    severity = "error"
    description = (
        "the whole-program lock-acquisition graph (with-nesting + cross-module "
        "call edges) must be acyclic"
    )

    def run(self, project: Project) -> List[Finding]:
        graph = build_lock_graph(project)
        findings: List[Finding] = []
        for cycle in graph.cycles():
            ring = cycle + [cycle[0]]
            first_edge = graph.edges[(ring[0], ring[1])]
            detail = "; ".join(
                f"{a} -> {b} ({graph.edges[(a, b)][2]} at {graph.edges[(a, b)][0]}:{graph.edges[(a, b)][1]})"
                for a, b in zip(ring, ring[1:])
            )
            findings.append(
                self.finding(
                    first_edge[0],
                    first_edge[1],
                    f"lock-order cycle: {' -> '.join(ring)} — {detail}",
                )
            )
        return findings
