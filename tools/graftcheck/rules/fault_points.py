"""fault-points: injection seams must stay tripped and tested.

Absorbs ``tools/check_fault_points.py`` (PR 1) as a graftcheck rule. For every
point in ``flink_ml_tpu.faults.FAULT_POINTS``:

1. the runtime has at least one ``faults.trip("<name>", ...)`` call site under
   ``flink_ml_tpu/`` (a registered point nobody trips is dead),
2. at least one test under ``tests/`` names the point (recovery paths CI never
   exercises are recovery paths that don't work),

and conversely every ``faults.trip(...)`` site names a registered point (a
typo'd name would only raise LookupError when reached). Trip sites are found
by AST (``faults.trip`` / bare ``trip`` imported from the faults module, first
argument a string literal); the test sweep is a substring scan because tests
arm points through several helpers (``faults.arm``, markers, config strings).
"""
from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, List, Tuple

from tools.graftcheck.engine import Finding, Project, Rule, register

FAULTS_MODULE_REL = "flink_ml_tpu/faults.py"


def _load_fault_points(repo_root: str) -> Dict:
    """FAULT_POINTS from ``<repo_root>/flink_ml_tpu/faults.py`` — always the
    analyzed tree's own file, never a ``flink_ml_tpu`` that happens to be
    importable, so fixture trees are analyzed against their own registry."""
    path = os.path.join(repo_root, FAULTS_MODULE_REL)
    spec = importlib.util.spec_from_file_location("_graftcheck_faults", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module.FAULT_POINTS


def _trip_name(node: ast.Call) -> str | None:
    func = node.func
    is_trip = (
        isinstance(func, ast.Attribute)
        and func.attr == "trip"
        and isinstance(func.value, ast.Name)
        and func.value.id == "faults"
    ) or (isinstance(func, ast.Name) and func.id == "trip")
    if is_trip and node.args and isinstance(node.args[0], ast.Constant):
        if isinstance(node.args[0].value, str):
            return node.args[0].value
    return None


def analyze(project: Project) -> Tuple[List[Tuple[str, str, int]], Dict[str, List[str]], set]:
    """(problems, trip_sites, tested). Problems are (message, rel, line).
    Trip sites come from the shared index facts (``facts["trip_sites"]``), so
    a cache-warm run discovers them without re-parsing a single file."""
    fault_points = _load_fault_points(project.repo_root)
    faults_sf = project.file(FAULTS_MODULE_REL)

    trip_sites: Dict[str, List[str]] = {}
    site_lines: Dict[str, Tuple[str, int]] = {}
    all_facts = project.facts()
    for sf in project.iter_files("flink_ml_tpu/"):
        if sf.rel == FAULTS_MODULE_REL:
            continue  # the framework itself (docstrings mention trip("<name>"))
        for point, lineno in all_facts.get(sf.rel, {}).get("trip_sites", []):
            trip_sites.setdefault(point, []).append(sf.rel)
            site_lines.setdefault(point, (sf.rel, lineno))

    tested = set()
    test_root = os.path.join(project.repo_root, "tests")
    for dirpath, _, filenames in os.walk(test_root):
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            with open(os.path.join(dirpath, name), encoding="utf-8") as f:
                content = f.read()
            for point in fault_points:
                if point in content:
                    tested.add(point)

    def registry_line(point: str) -> int:
        if faults_sf is not None:
            for lineno, line in enumerate(faults_sf.source.splitlines(), start=1):
                if f'"{point}"' in line or f"'{point}'" in line:
                    return lineno
        return 1

    problems: List[Tuple[str, str, int]] = []
    for point in sorted(fault_points):
        if point not in trip_sites:
            problems.append(
                (
                    f"fault point {point!r} is registered but has no "
                    "faults.trip() call site under flink_ml_tpu/",
                    FAULTS_MODULE_REL,
                    registry_line(point),
                )
            )
        if point not in tested:
            problems.append(
                (
                    f"fault point {point!r} is not exercised by any test under "
                    "tests/ — its recovery path is unproven",
                    FAULTS_MODULE_REL,
                    registry_line(point),
                )
            )
    for point in sorted(trip_sites):
        if point not in fault_points:
            rel, line = site_lines[point]
            problems.append(
                (
                    f"faults.trip({point!r}) at {trip_sites[point]} names an "
                    "unregistered fault point (typo?)",
                    rel,
                    line,
                )
            )
    return problems, trip_sites, tested


def check(repo_root: str) -> Tuple[List[str], Dict[str, List[str]]]:
    """The old ``tools/check_fault_points.py`` ``check()`` contract."""
    project = Project(repo_root, ["flink_ml_tpu"])
    problems, trip_sites, _ = analyze(project)
    return [p[0] for p in problems], trip_sites


@register
class FaultPointsRule(Rule):
    name = "fault-points"
    severity = "error"
    description = (
        "every registered fault point has a runtime trip site and a test; "
        "every trip site names a registered point"
    )

    def run(self, project: Project) -> List[Finding]:
        if project.file(FAULTS_MODULE_REL) is None:
            return []  # fixture trees without the faults registry: nothing to check
        problems, _, _ = analyze(project)
        return [self.finding(rel, line, msg) for msg, rel, line in problems]
