"""layer-deps: declarative layer map over ``flink_ml_tpu``; no upward imports.

The reference Flink ML encodes its discipline in Maven module boundaries
(``flink-ml-servable-core`` cannot see ``flink-ml-lib``); a single Python
package has no compiler-enforced equivalent, so this rule carries the layer
map explicitly:

    L0 foundation          config, utils, faults, metrics, native
    L1 compute / servable  linalg, params, api, ops, checkpoint, parallel,
                           servable, serving, trace
    L2 runtime             iteration, execution, builder
    L3 library             models, benchmark, loop, loadgen, the root package

A module may import same-layer or lower — importing *up* is the violation
(a servable-tier file importing the runtime, a kernel importing a model).
Three modules live at a different layer than their package (``MODULE_LAYERS``):
``ops.optimizer`` / ``native.cache`` / ``parallel.datastream_utils`` are
runtime-coupled (they import the iteration tier) and sit at L2, which is why
``ops/kernels.py`` — not ``ops/optimizer.py`` — is what the servable tier may
use. ``serving.plan`` (the compiled fast path) deliberately sits at the
package's L1: it composes ``servable`` kernel specs and ``ops/kernels.py``
``*_fn`` bodies only, so the runtime-free guarantee covers the fused
executables too. Imports *within* one top-level subpackage are not layered (a
package's internal structure is its own business), and an import of an
unmapped ``flink_ml_tpu`` subpackage is itself a finding so the map cannot
silently rot.

This rule generalizes and absorbs ``tools/check_servable_imports.py``: the L1
runtime-free guarantee (servable/serving never import iteration / execution /
builder / models, even lazily) is the ``layer(servable)=1 < layer(runtime)``
special case. :func:`servable_violations_in_file` keeps the old tool's exact
file-level contract for its shim and tests.
"""
from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional, Tuple

from tools.graftcheck.engine import Finding, Project, Rule, SourceFile, register

ROOT_PACKAGE = "flink_ml_tpu"

LAYER_NAMES = {0: "foundation", 1: "compute/servable", 2: "runtime", 3: "library"}

#: Layer of each top-level subpackage (or root-level module) of ROOT_PACKAGE.
PACKAGE_LAYERS = {
    "config": 0,
    "utils": 0,
    "faults": 0,
    "metrics": 0,
    "native": 0,
    "linalg": 1,
    "params": 1,
    "api": 1,
    "ops": 1,
    "checkpoint": 1,
    "parallel": 1,
    "servable": 1,
    "serving": 1,
    # graftscope tracing: consumed by every tier including the L1 serving
    # fast path, so it sits at L1 itself and only imports L0 (config,
    # metrics) — the runtime-free guarantee covers instrumented servables.
    "trace": 1,
    # The always-on flight recorder (journal / incidents / HTTP endpoint):
    # instrumented by the serving tier and the fast-path planners, so it
    # sits at L1 like trace and imports only L0 (config, faults, metrics)
    # plus trace itself. The L0 faults module reaches it through its
    # observer hook — never by importing upward.
    "telemetry": 1,
    "iteration": 2,
    "execution": 2,
    "builder": 2,
    "models": 3,
    "benchmark": 3,
    # The open-loop load harness drives the serving tier from the outside
    # (schedules, offered-load ramps, chaos accounting) — a measurement rig
    # over L1, not a dependency of it, so it sits at the library layer like
    # benchmark; nothing below may import it.
    "loadgen": 3,
    # The continuous-learning loop composes the serving tier's publish/swap
    # machinery WITH the model library's online estimators and the execution
    # supervisor, so it sits above all of them at the library layer — the
    # serving-tier pieces it drives (registry, poller, fast path) stay at L1
    # and keep their runtime-free guarantee; the loop is the one place the
    # two halves are allowed to meet (docs/continuous.md).
    "loop": 3,
    # Fleet serving composes L1 serving replicas with the L2 execution
    # supervisor's restart strategies and the L3 loop's drift/rollback
    # machinery (canary verdicts), so it sits at the library layer with
    # loop/loadgen — a single replica never knows it is part of a fleet,
    # and nothing below L3 may import the fleet tier (docs/fleet.md).
    "fleet": 3,
    # The retrieval tier (CandidateIndex + RetrievalClient) sits at the
    # library layer with models/fleet, but by contract imports only L0/L1
    # (api, linalg, params, servable, utils) — a published index loads in a
    # serving process with no training stack present (docs/retrieval.md).
    "retrieval": 3,
    # the root package surface (flink_ml_tpu/__init__.py) re-exports the API
    "": 3,
}

#: Module-granular overrides (longest prefix wins over PACKAGE_LAYERS).
MODULE_LAYERS = {
    "ops.optimizer": 2,  # fused trainers: imports iteration at module level
    "native.cache": 2,  # native-backed datacache: reaches into iteration.datacache
    "parallel.datastream_utils": 2,  # external sort / co-group over HostDataCache
    # The batch fast path sits at builder's own L2 but only consumes L0/L1
    # (servable.planner + kernel specs, api, config, metrics) — registered
    # explicitly so the fused batch tier's dependency story is auditable.
    "builder.batch_plan": 2,
    # Mesh placement for compiled plans (pod-scale fan-out): L1 like the
    # rest of servable — it may import parallel.mesh (same layer) but stays
    # inside the runtime-free guarantee; registered explicitly so the
    # sharded fast paths' dependency story is auditable.
    "servable.sharding": 1,
    # The persistent compiled-plan cache: L1 like the rest of servable — it
    # imports only L0 (config, faults, metrics) plus telemetry (same layer),
    # so the runtime-free guarantee covers cache-served executables too.
    # Its load/store surfaces are `# graftcheck: cold` and the host-sync
    # rule's file-I/O scope proves no hot root can reach cache disk I/O.
    "servable.plancache": 1,
    # The runtime-free retrieval serving heads (top-K over a published
    # CandidateIndex): L1 like the rest of servable — they import only L0
    # plus same-layer servable/ops/api/linalg/params modules. Registered
    # explicitly because the training-side models/feature/lsh.py imports
    # HASH_PRIME *from* here (L3 → L1, allowed), never the reverse.
    "servable.retrieval": 1,
    # Training-side mesh placement (the TrainSharding companion of
    # servable.sharding): L1 like the rest of parallel — it imports only L0
    # (config lazily, metrics) plus same-package mesh/collectives, and the
    # trainers that consume it (ops.optimizer L2, models L3) import DOWN into
    # it. Registered explicitly so the deterministic training tier's
    # dependency story is auditable next to its serving twin.
    "parallel.train_sharding": 1,
}

#: The absorbed check_servable_imports.py contract (see module docstring).
RUNTIME_FREE_PACKAGES = ("flink_ml_tpu/servable", "flink_ml_tpu/serving")
FORBIDDEN_PREFIXES = (
    "flink_ml_tpu.iteration",
    "flink_ml_tpu.execution",
    "flink_ml_tpu.builder",
    "flink_ml_tpu.models",
)


def layer_of(subpath: str) -> Optional[int]:
    """Layer of a dotted path under ROOT_PACKAGE ('' = the root package).
    None when the first component is not in the map."""
    if subpath in MODULE_LAYERS:
        return MODULE_LAYERS[subpath]
    return PACKAGE_LAYERS.get(subpath.split(".", 1)[0] if subpath else "")


def iter_imports(sf: SourceFile) -> Iterable[Tuple[int, str]]:
    """Yield (lineno, absolute dotted module) for every import in ``sf``,
    with relative imports resolved against the file's module path and
    ``from pkg import sub`` expanded to ``pkg.sub`` (the importing code
    cannot know statically whether ``sub`` is a module or a symbol; for
    layering the longer path is looked up first and falls back)."""
    is_init = sf.rel.endswith("/__init__.py")
    parts = sf.module.split(".")
    package = parts if is_init else parts[:-1]
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield node.lineno, alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = package[: len(package) - (node.level - 1)]
                mod = ".".join(base + ([node.module] if node.module else []))
            else:
                mod = node.module or ""
            if not mod:
                continue
            yield node.lineno, mod
            for alias in node.names:
                yield node.lineno, f"{mod}.{alias.name}"


def _subpath(module: str) -> Optional[str]:
    if module == ROOT_PACKAGE:
        return ""
    if module.startswith(ROOT_PACKAGE + "."):
        return module[len(ROOT_PACKAGE) + 1 :]
    return None


@register
class LayerDepsRule(Rule):
    name = "layer-deps"
    severity = "error"
    granularity = "file"
    cache_version = 7  # v7: training-sharding tier registered (parallel.train_sharding L1)
    description = (
        "imports within flink_ml_tpu must not point at a higher layer "
        "(foundation < compute/servable < runtime < library)"
    )

    def check_file(self, project: Project, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        if not sf.rel.startswith(ROOT_PACKAGE + "/"):
            return findings
        facts = project.facts().get(sf.rel)
        if facts is None:
            return findings
        src_sub = _subpath(sf.module)
        if src_sub is not None:
            src_layer = layer_of(src_sub)
            if src_layer is None:
                findings.append(
                    self.finding(
                        sf.rel,
                        1,
                        f"module {sf.module} is not in the layer map — add its "
                        "top-level package to PACKAGE_LAYERS",
                    )
                )
                return findings
            seen = set()
            for lineno, module in facts["imports"]:
                dst_sub = _subpath(module)
                if dst_sub is None:
                    continue  # stdlib / third-party
                # Intra-package imports are the package's own structure.
                if dst_sub and src_sub and dst_sub.split(".")[0] == src_sub.split(".")[0]:
                    continue
                dst_layer = layer_of(dst_sub)
                if dst_layer is None:
                    # ``from pkg import symbol`` expansion of an unmapped name:
                    # only report genuinely unmapped *packages*.
                    if layer_of(dst_sub.split(".", 1)[0]) is None and (lineno, dst_sub.split(".")[0]) not in seen:
                        seen.add((lineno, dst_sub.split(".")[0]))
                        findings.append(
                            self.finding(
                                sf.rel,
                                lineno,
                                f"import of {module} — not in the layer map; add it "
                                "to PACKAGE_LAYERS",
                            )
                        )
                    continue
                already = any(
                    ln == lineno and (dst_sub == flagged or dst_sub.startswith(flagged + "."))
                    for ln, flagged in seen
                )
                if dst_layer > src_layer and not already:
                    seen.add((lineno, dst_sub))
                    findings.append(
                        self.finding(
                            sf.rel,
                            lineno,
                            f"{sf.module} (L{src_layer} {LAYER_NAMES[src_layer]}) imports "
                            f"{ROOT_PACKAGE}.{dst_sub} (L{dst_layer} {LAYER_NAMES[dst_layer]}) "
                            "— upward imports break the layer discipline",
                        )
                    )
        return findings


# -- check_servable_imports.py compatibility surface -------------------------


def _forbidden(module: str) -> bool:
    return any(module == p or module.startswith(p + ".") for p in FORBIDDEN_PREFIXES)


def servable_violations_in_file(path: str) -> Iterable[Tuple[int, str]]:
    """The old tool's exact per-file semantics: (lineno, module) for every
    import of a training-stack root, lazy (function-local) imports included;
    relative imports skipped (the servable tier has no runtime subpackages)."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if _forbidden(alias.name):
                    yield node.lineno, alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                continue
            module = node.module or ""
            if _forbidden(module):
                yield node.lineno, module
            elif module == ROOT_PACKAGE:
                for alias in node.names:
                    if _forbidden(f"{ROOT_PACKAGE}.{alias.name}"):
                        yield node.lineno, f"{ROOT_PACKAGE}.{alias.name}"


def servable_check(repo_root: str) -> Tuple[List[str], List[str]]:
    """(problems, checked_files) over the runtime-free packages — the body of
    the old ``tools/check_servable_imports.py`` ``check()``."""
    problems: List[str] = []
    checked: List[str] = []
    for package in RUNTIME_FREE_PACKAGES:
        pkg_dir = os.path.join(repo_root, package)
        for dirpath, _, filenames in os.walk(pkg_dir):
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                rel = os.path.relpath(path, repo_root)
                checked.append(rel)
                for lineno, module in servable_violations_in_file(path):
                    problems.append(
                        f"{rel}:{lineno} imports {module} — the serving tier "
                        "must not depend on the training stack (L1 "
                        "runtime-free guarantee)"
                    )
    if not checked:
        problems.append("no files checked — package layout changed?")
    return problems, checked
