"""recompile-hazard: no jit cache churn on hot or iterated paths.

On TPU an XLA recompile is a multi-second hot-path stall, and the subtle part
is that nothing *looks* wrong at the call site — the three hazard shapes this
rule catches all type-check, run, and silently destroy goodput ("ML
Productivity Goodput", PAPERS.md, measures exactly this waste):

1. **jit wrappers constructed per call** — ``jax.jit(lambda ...)`` or
   ``jit(local_fn)`` built inside a loop, or anywhere on a hot region
   (reachable from a ``# graftcheck: hot-root``), or immediately invoked
   (``jit(f)(x)``). jit's trace cache keys on function identity: a fresh
   lambda/closure each iteration is a fresh cache entry — a recompile every
   time. The sanctioned patterns are exempt: a ``functools.cache``/
   ``lru_cache``-memoized factory (the ``ops/kernels.py`` ``*_kernel``
   convention — one wrapper per config tuple, ever) and module-scope
   construction.
2. **varying Python scalars fed to jitted calls without ``static_argnums``**
   — a ``range``/``enumerate`` counter passed straight into a jitted function
   becomes a fresh trace-time constant signature per value. The repo's
   convention is to burn config scalars into a cached factory's closure or
   declare them static; feeding them raw churns the cache.
3. **Python branching on traced values inside jitted functions** — an
   ``if p > 0:`` on a non-static parameter either raises a TracerError or, if
   the value happens to be concrete, silently specializes the executable per
   outcome (shape-dependent branching being the classic case). Reads of
   ``p.shape`` / ``p.ndim`` / ``p.dtype`` are static metadata and exempt;
   ``jnp.where`` / ``lax.cond`` are the traced alternatives.

Scope: the jitted tiers (``ops/``, ``models/``, ``parallel/``, ``servable/``,
``serving/``, ``builder/``) — the same surface jit-purity polices, now with
the index's call graph deciding what is hot.
"""
from __future__ import annotations

from typing import List

from tools.graftcheck.engine import Finding, Project, Rule, register

SCOPE_PREFIXES = (
    "flink_ml_tpu/ops/",
    "flink_ml_tpu/models/",
    "flink_ml_tpu/parallel/",
    "flink_ml_tpu/servable/",
    "flink_ml_tpu/serving/",
    "flink_ml_tpu/builder/",
)


@register
class RecompileHazardRule(Rule):
    name = "recompile-hazard"
    severity = "error"
    description = (
        "no per-call jit construction (loops / hot regions / jit(f)(x)), no "
        "varying Python scalars into jitted calls without static_argnums, no "
        "Python branching on traced values inside jitted functions"
    )

    def run(self, project: Project) -> List[Finding]:
        index = project.index
        roots = [
            node
            for _facts, node, ff in index.iter_functions()
            if "hot-root" in ff["marks"]
        ]
        hot = index.reachable(roots) if roots else {}
        findings: List[Finding] = []
        for f, node, ff in index.iter_functions():
            rel = f["rel"]
            if not any(rel.startswith(p) for p in SCOPE_PREFIXES):
                continue
            findings.extend(self._check_function(index, f, node, ff, hot))
        return findings

    def _check_function(self, index, f, node, ff, hot) -> List[Finding]:
        out: List[Finding] = []
        rel = f["rel"]
        name = ff["name"]

        # 1. per-call jit construction
        for line, form, _binding, in_loop in ff["jit_sites"]:
            if ff["memoized"]:
                continue  # the cached-factory convention: one wrapper, ever
            what = {
                "lambda": "a jit-wrapped lambda",
                "named": "a jit wrapper",
                "bare": "a jit wrapper",
                "immediate": "a jit wrapper",
            }[form]
            if form == "immediate":
                out.append(
                    self.finding(
                        rel,
                        line,
                        f"`{name}` constructs AND invokes {what} in one "
                        "expression (jit(f)(...)) — a fresh trace-cache entry "
                        "per call, i.e. a recompile every time; jit once at "
                        "module scope or behind functools.cache",
                    )
                )
            elif in_loop:
                out.append(
                    self.finding(
                        rel,
                        line,
                        f"`{name}` constructs {what} inside a loop — each "
                        "iteration creates a new callable identity and a "
                        "fresh jit cache entry (recompile per iteration); "
                        "hoist the jit out of the loop or memoize the factory",
                    )
                )
            elif node in hot:
                root = hot[node].replace(":", ".")
                out.append(
                    self.finding(
                        rel,
                        line,
                        f"`{name}` constructs {what} on a hot region "
                        f"(reachable from hot-root {root}) — per-request jit "
                        "construction recompiles on every call; build it at "
                        "plan/warmup time (`# graftcheck: cold`) instead",
                    )
                )

        # 2. varying Python scalars into jitted calls without static_argnums
        for callee, line, loop_args in ff["jitted_call_sites"]:
            target = f["functions"].get(callee)
            is_jitted = bool(target and target["is_jitted"])
            has_static = bool(target and target["has_static"])
            if not is_jitted and callee in f.get("jit_bound", {}):
                is_jitted = True
                has_static = f["jit_bound"][callee]["static"]
            if is_jitted and not has_static:
                args = ", ".join(sorted(set(loop_args)))
                out.append(
                    self.finding(
                        rel,
                        line,
                        f"jitted `{callee}` is fed varying Python scalar(s) "
                        f"`{args}` (loop counters) without static_argnums — "
                        "each value becomes a fresh trace signature; declare "
                        "them static or burn them into a cached factory",
                    )
                )

        # 3. Python branching on traced values inside jitted functions
        if ff["is_jitted"]:
            static = set(ff["static_names"])
            for line, names in ff["param_branches"]:
                dyn = sorted(n for n in names if n not in static)
                if not dyn:
                    continue
                if ff["has_static"] and not ff["static_names"]:
                    continue  # statics declared but not statically parseable
                out.append(
                    self.finding(
                        rel,
                        line,
                        f"jitted `{name}` branches in Python on traced "
                        f"value(s) {', '.join(dyn)} — shape/value-dependent "
                        "control flow re-specializes (or TracerErrors) per "
                        "outcome; use jnp.where/lax.cond or mark the argument "
                        "static",
                    )
                )
        return out
