"""fusion-tier: the exact tier never spans a reduction; Pallas only behind fast.

``fusion.mode`` (docs/fusion.md) is a numerics contract enforced by code
*shape*: the exact tier's program partition
(``servable/planner.py::_partition_exact``) may merge only consecutive
``elementwise`` specs, and the relaxed-numerics machinery — the
cross-reduction ``_partition_fast`` and the Pallas megakernels
(``servable/megakernels.py``) — must be reachable only behind the fast tier.
A refactor that let the exact partition merge on ``fusable`` (the fast
vocabulary), or that called the megakernel builder outside a
``fusion.fast`` guard, would silently move the default tier onto
ulp-envelope numerics. This rule pins three invariants statically:

1. **Exact partition purity** — ``_partition_exact`` must exist, must gate
   its merge on ``.elementwise``, and must not reference the fast
   vocabulary (``.fusable``, ``.fusion_op``, the fast partition/megakernel
   helpers, or the fused/megakernel plan kinds). Composed with the
   ``elementwise-claim`` rule (every ``elementwise=True`` body is
   reduction-free, callees included), this proves the exact tier's program
   partitions never span a reduction primitive — the extension of the PR 6
   elementwise machinery to the planner's partition output.

2. **Pallas containment** — within the plan tier (``servable/``,
   ``serving/``, ``builder/``), only ``servable/megakernels.py`` may import
   or reference Pallas. Kernel code elsewhere in the tree (``ops/``,
   ``parallel/``, model internals) is out of scope — those are training
   kernels with their own rules.

3. **Fast gating** — every planner reference to the megakernel module and
   every call of the fast-partition helpers (``_partition_fast``,
   ``_fast_megakernels``) must sit either inside those helpers themselves
   or under an ``if`` whose test reads the tier's ``.fast`` flag (or the
   ``FUSION_FAST`` constant). The megakernel import itself must be
   function-local to a fast helper — module import time must not pay for
   (or expose) Pallas on the exact tier.

Zero suppressions: the shipped tree satisfies all three by construction.

File granularity: every check reads only the file it fires in (the planner's
gating is self-contained — the megakernel import names are bound inside
planner.py itself), so findings cache per content hash and a warm run parses
nothing (the PR 6 cache contract).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.graftcheck.engine import Finding, Project, Rule, SourceFile, register

PLANNER_REL = "flink_ml_tpu/servable/planner.py"
MEGAKERNELS_REL = "flink_ml_tpu/servable/megakernels.py"
PLAN_TIER_PREFIXES = (
    "flink_ml_tpu/servable/",
    "flink_ml_tpu/serving/",
    "flink_ml_tpu/builder/",
)
#: The only planner functions allowed to touch the megakernel module.
FAST_HELPERS = {"_partition_fast", "_fast_megakernels"}
#: Fast-tier vocabulary the exact partition must never read.
FAST_ATTRS = {"fusable", "fusion_op"}
FAST_NAMES = {"PLAN_FUSED", "PLAN_MEGAKERNEL", "FUSION_FAST"} | FAST_HELPERS


def _test_reads_fast(test: ast.AST) -> bool:
    """Whether an ``if`` test reads the fast-tier switch: an attribute
    ``.fast`` / ``.megakernel`` access or the FUSION_FAST constant."""
    for n in ast.walk(test):
        if isinstance(n, ast.Attribute) and n.attr in ("fast", "megakernel"):
            return True
        if isinstance(n, ast.Name) and n.id == "FUSION_FAST":
            return True
    return False


def _pallas_imports(tree: ast.AST) -> List[ast.AST]:
    out = []
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            if any("pallas" in (a.name or "") for a in n.names):
                out.append(n)
        elif isinstance(n, ast.ImportFrom):
            mod = n.module or ""
            if "pallas" in mod or any("pallas" in (a.name or "") for a in n.names):
                out.append(n)
    return out


@register
class FusionTierRule(Rule):
    name = "fusion-tier"
    severity = "error"
    description = (
        "exact-mode program partitions merge only on elementwise (never span "
        "a reduction), Pallas stays inside servable/megakernels.py, and "
        "megakernel machinery is reachable only behind the fast fusion tier"
    )
    granularity = "file"
    cache_version = 1

    def check_file(self, project: Project, sf: SourceFile) -> List[Finding]:
        if not sf.rel.startswith(PLAN_TIER_PREFIXES) or sf.rel == MEGAKERNELS_REL:
            return []
        if sf.tree is None:
            return []
        findings: List[Finding] = []

        # -- 2: Pallas containment in the plan tier ---------------------------
        for node in _pallas_imports(sf.tree):
            findings.append(
                self.finding(
                    sf.rel,
                    node.lineno,
                    "Pallas import in the plan tier outside "
                    f"{MEGAKERNELS_REL} — megakernel bodies (and their "
                    "dependency on Pallas) live only there, reachable "
                    "only behind fusion.mode=fast",
                )
            )

        if sf.rel != PLANNER_REL:
            return findings
        planner = sf

        # -- 1: exact partition purity ---------------------------------------
        exact_def: Optional[ast.FunctionDef] = None
        for n in ast.walk(planner.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n.name == "_partition_exact":
                exact_def = n
                break
        if exact_def is None:
            findings.append(
                self.finding(
                    PLANNER_REL,
                    1,
                    "planner has no _partition_exact function — the exact "
                    "tier's partition must be a named, statically checkable "
                    "unit",
                )
            )
        else:
            reads_elementwise = any(
                isinstance(n, ast.Attribute) and n.attr == "elementwise"
                for n in ast.walk(exact_def)
            )
            if not reads_elementwise:
                findings.append(
                    self.finding(
                        PLANNER_REL,
                        exact_def.lineno,
                        "_partition_exact never tests .elementwise — the "
                        "exact tier's only legal merge condition (the "
                        "bit-exactness contract)",
                    )
                )
            for n in ast.walk(exact_def):
                if isinstance(n, ast.Attribute) and n.attr in FAST_ATTRS:
                    findings.append(
                        self.finding(
                            PLANNER_REL,
                            n.lineno,
                            f"_partition_exact reads the fast-tier attribute "
                            f".{n.attr} — exact partitions may merge only on "
                            ".elementwise, never across a reduction boundary",
                        )
                    )
                elif isinstance(n, ast.Name) and n.id in FAST_NAMES:
                    findings.append(
                        self.finding(
                            PLANNER_REL,
                            n.lineno,
                            f"_partition_exact references fast-tier machinery "
                            f"{n.id} — the exact tier must not reach relaxed-"
                            "numerics code",
                        )
                    )

        # -- 3: fast gating of megakernel reachability ------------------------
        mega_bound: Set[str] = set()
        for n in ast.walk(planner.tree):
            if isinstance(n, ast.ImportFrom) and (n.module or "").endswith(
                "servable.megakernels"
            ):
                mega_bound.update(a.asname or a.name for a in n.names)
        findings.extend(self._check_gating(planner, mega_bound))
        return findings

    def _check_gating(self, planner, mega_bound: Set[str]) -> List[Finding]:
        """Walk the planner with an ancestor stack: references to megakernel
        imports / fast helpers are legal only inside the fast helpers or
        under an ``if`` that reads the fast switch."""
        findings: List[Finding] = []
        watched = mega_bound | FAST_HELPERS

        def visit(node: ast.AST, in_fast_helper: bool, guarded: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                in_helper = in_fast_helper or node.name in FAST_HELPERS
                for child in ast.iter_child_nodes(node):
                    visit(child, in_helper, False)  # a def resets if-guards
                return
            if isinstance(node, ast.If):
                child_guard = guarded or _test_reads_fast(node.test)
                for child in node.body:
                    visit(child, in_fast_helper, child_guard)
                for child in node.orelse:
                    visit(child, in_fast_helper, guarded)
                visit(node.test, in_fast_helper, guarded)
                return
            if isinstance(node, ast.ImportFrom) and (node.module or "").endswith(
                "servable.megakernels"
            ):
                if not in_fast_helper:
                    findings.append(
                        self.finding(
                            PLANNER_REL,
                            node.lineno,
                            "megakernel import outside the fast helpers — the "
                            "import must be function-local to "
                            f"{sorted(FAST_HELPERS)} so the exact tier never "
                            "pays for (or reaches) Pallas",
                        )
                    )
            elif isinstance(node, ast.Name) and node.id in watched:
                if not (in_fast_helper or guarded):
                    findings.append(
                        self.finding(
                            PLANNER_REL,
                            node.lineno,
                            f"{node.id} referenced outside a fusion-fast guard "
                            "— megakernel/fast-partition machinery must be "
                            "reachable only behind an `if <tier>.fast` test "
                            "or inside the fast helpers themselves",
                        )
                    )
            for child in ast.iter_child_nodes(node):
                visit(child, in_fast_helper, guarded)

        visit(planner.tree, False, False)
        return findings
