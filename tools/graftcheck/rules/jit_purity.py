"""jit-purity: no host syncs or impure host calls inside jitted functions.

JAX transformations assume functionally pure traced code (DrJAX,
arXiv:2403.07128 §2); a stray ``.item()`` or ``float(tracer)`` inside a
``jax.jit`` silently inserts a device→host sync on every call — exactly the
goodput leak the serving tier's micro-batching exists to avoid — and
``time.time()`` / ``np.random.*`` burn their value into the compiled
executable at trace time, so the "dynamic" value is a constant forever after.

Scope: functions *statically recognizable* as jitted inside ``ops/``,
``models/``, ``parallel/``, ``servable/``, ``serving/`` and ``builder/``
(both fast paths compose kernel specs into fused AOT executables — an impure
call there is burned into every per-bucket / per-chunk program) — decorated
with ``jit``
/ ``jax.jit`` / ``partial(jax.jit, ...)`` (bare or called), or passed by name
to a ``jit(...)`` call in the same module. Flagged inside their bodies:

- ``<x>.item()``                      — device→host sync per call
- ``float(p)`` / ``int(p)`` / ``bool(p)`` on a function parameter
                                      — concretizes a tracer (TracerError or sync)
- ``np.asarray`` / ``np.array`` on a function parameter
                                      — host materialization of a traced value
- ``time.time()`` & friends           — trace-time constant, not a clock
- ``np.random.*``                     — host RNG; thread a ``jax.random`` key
- ``print(...)``                      — host I/O at trace time; use
                                        ``jax.debug.print`` if needed

Heuristic by design: a helper jitted from another module is not seen, and
numpy on *static* values inside a jitted function is legal — which is why the
numpy/float checks only fire on direct function parameters.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from tools.graftcheck.engine import Finding, Project, Rule, SourceFile, register

SCOPE_PREFIXES = (
    "flink_ml_tpu/ops/",
    "flink_ml_tpu/models/",
    "flink_ml_tpu/parallel/",
    "flink_ml_tpu/servable/",
    "flink_ml_tpu/serving/",
    # the batch fast path composes kernel specs into fused AOT chains, same
    # stakes as serving/ — an impure call would burn into every chunk program
    "flink_ml_tpu/builder/",
    # the continuous loop drives serving swaps + eval traffic: any jitted fn
    # it introduces carries the serving tier's purity stakes
    "flink_ml_tpu/loop/",
    # graftscope: the tracer is called from inside every hot region — a
    # jitted helper here would burn into all four tiers at once
    "flink_ml_tpu/trace",
)

_TIME_ATTRS = {"time", "perf_counter", "monotonic", "time_ns", "perf_counter_ns"}


def _is_jit_expr(node: ast.AST, jax_names: Set[str]) -> bool:
    """``jit`` (imported from jax) or ``<jax alias>.jit``."""
    if isinstance(node, ast.Name):
        return node.id in jax_names
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return isinstance(node.value, ast.Name)
    return False


def _module_aliases(tree: ast.AST) -> Dict[str, Set[str]]:
    """Track how numpy / time / jax.jit are spelled in this module."""
    np_names: Set[str] = set()
    time_names: Set[str] = set()
    time_funcs: Set[str] = set()
    jit_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name
                if alias.name == "numpy":
                    np_names.add(bound)
                elif alias.name == "time":
                    time_names.add(bound)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time":
                for alias in node.names:
                    if alias.name in _TIME_ATTRS:
                        time_funcs.add(alias.asname or alias.name)
            elif node.module == "jax":
                for alias in node.names:
                    if alias.name == "jit":
                        jit_names.add(alias.asname or alias.name)
    return {"np": np_names, "time": time_names, "time_funcs": time_funcs, "jit": jit_names}


def _is_jitted(fn: ast.AST, jit_names: Set[str]) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        if _is_jit_expr(dec, jit_names):
            return True
        if isinstance(dec, ast.Call):
            if _is_jit_expr(dec.func, jit_names):
                return True  # @jax.jit(static_argnums=...)
            is_partial = (isinstance(dec.func, ast.Name) and dec.func.id == "partial") or (
                isinstance(dec.func, ast.Attribute) and dec.func.attr == "partial"
            )
            if is_partial and any(_is_jit_expr(a, jit_names) for a in dec.args):
                return True
    return False


def jitted_functions(sf: SourceFile, jit_names: Set[str]) -> List[ast.AST]:
    """FunctionDefs decorated as jitted, plus ones passed by name to a
    ``jit(...)`` call anywhere in the module."""
    defs: Dict[str, List[ast.AST]] = {}
    out: List[ast.AST] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
            if _is_jitted(node, jit_names):
                out.append(node)
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func, jit_names) and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name):
                for fn in defs.get(target.id, []):
                    if fn not in out:
                        out.append(fn)
    return out


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    names.discard("self")
    return names


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    severity = "error"
    granularity = "file"
    cache_version = 2  # v2: file-granularity (findings cached per content hash)
    description = (
        "no host syncs (.item(), float(tracer), np.asarray) or impure host "
        "calls (time.time, np.random, print) inside jitted functions"
    )

    def check_file(self, project: Project, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        if not any(sf.rel.startswith(p) for p in SCOPE_PREFIXES):
            return findings
        if sf.tree is None:
            return findings  # parse error reported by the engine
        aliases = _module_aliases(sf.tree)
        for fn in jitted_functions(sf, aliases["jit"]):
            findings.extend(self._check_function(sf, fn, aliases))
        return findings

    def _check_function(self, sf: SourceFile, fn: ast.AST, aliases) -> List[Finding]:
        out: List[Finding] = []
        params = _param_names(fn)
        where = f"jitted `{fn.name}`"

        def flag(node: ast.AST, msg: str) -> None:
            out.append(self.finding(sf.rel, node.lineno, f"{where}: {msg}"))

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr == "item" and not node.args:
                    flag(node, ".item() forces a device->host sync on every call")
                elif (
                    isinstance(func.value, ast.Name)
                    and func.value.id in aliases["time"]
                    and func.attr in _TIME_ATTRS
                ):
                    flag(
                        node,
                        f"{func.value.id}.{func.attr}() is evaluated once at trace "
                        "time and burned into the executable — pass time in as an "
                        "argument or read it outside jit",
                    )
                elif (
                    isinstance(func.value, ast.Attribute)
                    and func.value.attr == "random"
                    and isinstance(func.value.value, ast.Name)
                    and func.value.value.id in aliases["np"]
                ):
                    flag(
                        node,
                        f"np.random.{func.attr} is host RNG fixed at trace time — "
                        "thread a jax.random key instead",
                    )
                elif (
                    isinstance(func.value, ast.Name)
                    and func.value.id in aliases["np"]
                    and func.attr in ("asarray", "array")
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in params
                ):
                    flag(
                        node,
                        f"np.{func.attr}({node.args[0].id}) materializes a traced "
                        "argument on the host — use jnp, or convert before jit",
                    )
            elif isinstance(func, ast.Name):
                if func.id == "print":
                    flag(
                        node,
                        "print() runs at trace time only — use jax.debug.print or "
                        "log outside jit",
                    )
                elif func.id in aliases["time_funcs"]:
                    flag(node, f"{func.id}() is a wall-clock read fixed at trace time")
                elif (
                    func.id in ("float", "int", "bool")
                    and len(node.args) == 1
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in params
                ):
                    flag(
                        node,
                        f"{func.id}({node.args[0].id}) concretizes a traced argument "
                        "(TracerError or a silent host sync)",
                    )
        return out
