"""check-then-act: a guarded decision must not go stale before its write.

A consistent lockset (shared-state-guard) is necessary but not sufficient:
``if self._n < cap`` under the lock, release, then ``self._n += 1`` under a
*second* acquisition is still a race — another thread interleaves between
the regions and the decision is stale by the time the write lands (the
classic TOCTOU lost-update). This rule flags, **within one function**, a
read of a shared, lock-guarded attribute in one lock region followed by a
write to the same attribute under a separate acquisition of the same lock.

Composition: the shared substrate (per-attr accesses, effective locksets,
thread roles) comes from shared-state-guard's class-state collection; only
attributes that are actually *shared* (≥ 2 roles, or a multi role) and
*consistently guarded* are candidates — an unguarded attribute is already a
shared-state-guard error, and a single-role attribute cannot interleave.

Scope is intra-procedural by design (the RacerD trade-off): a read region in
one method and a write region in another is a normal guarded API (``should_
shed`` deciding, ``record_shed`` recording); the atomicity obligation the
rule enforces is the one a *single* function visibly splits.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from tools.graftcheck.engine import Finding, Project, Rule, register
from tools.graftcheck.rules.lock_order import _lock_id
from tools.graftcheck.rules.shared_state_guard import (
    AttrAccess,
    collect_class_states,
    shared_roles,
)
from tools.graftcheck.topology import topology_for


def _region_for(access: AttrAccess, lock: str, module: str, cls: str) -> Optional[str]:
    """The lexical region id of ``lock`` at this access, or None when the
    lock is only held through the interprocedural context (the caller's
    region — not splittable within this function)."""
    for region in access.regions:
        token, _, line = region.rpartition("@")
        if _lock_id(module, cls, token) == lock:
            return region
    return None


@register
class CheckThenActRule(Rule):
    name = "check-then-act"
    severity = "error"
    description = (
        "a read-decide-write of one shared, guarded attribute must not be "
        "split across separate acquisitions of its lock within one function"
    )

    def run(self, project: Project) -> List[Finding]:
        topo = topology_for(project)
        findings: List[Finding] = []
        for state in collect_class_states(project):
            if state.cfacts.get("attr_marks"):
                marked = set(state.cfacts["attr_marks"])
            else:
                marked = set()
            for attr in sorted(state.attrs):
                accesses = [a for a in state.attrs[attr] if not a.in_init]
                if not accesses or attr in marked:
                    continue
                if not any(a.is_write for a in accesses):
                    continue
                roles = shared_roles(topo, accesses)
                if roles is None:
                    continue
                common = frozenset.intersection(*(a.locks for a in accesses))
                if not common:
                    continue  # shared-state-guard's problem, not ours
                for lock in sorted(common):
                    findings.extend(
                        self._check_attr(state, attr, lock, accesses, topo, roles)
                    )
        return findings

    def _check_attr(self, state, attr, lock, accesses, topo, roles) -> List[Finding]:
        # Group this attribute's accesses per function, then per lexical
        # region of `lock` within that function.
        by_fn: Dict[str, List[AttrAccess]] = {}
        for a in accesses:
            by_fn.setdefault(a.qual, []).append(a)
        out: List[Finding] = []
        for qual in sorted(by_fn):
            regions: Dict[str, Dict[str, int]] = {}
            for a in by_fn[qual]:
                region = _region_for(a, lock, state.module, state.cls)
                if region is None:
                    continue
                info = regions.setdefault(region, {})
                if a.is_write:
                    info["write"] = min(info.get("write", a.line), a.line)
                else:
                    info["read"] = min(info.get("read", a.line), a.line)
            if len(regions) < 2:
                continue
            read_only = [
                (info["read"], region)
                for region, info in regions.items()
                if "read" in info and "write" not in info
            ]
            writes = [
                (info["write"], region)
                for region, info in regions.items()
                if "write" in info
            ]
            if not read_only or not writes:
                continue
            read_line, read_region = min(read_only)
            later = [(line, region) for line, region in writes if line > read_line and region != read_region]
            if not later:
                continue
            write_line, _ = min(later)
            out.append(
                self.finding(
                    state.rel,
                    write_line,
                    f"check-then-act: {state.module}.{qual} reads "
                    f"{state.cls}.{attr} under {lock} (line {read_line}) and "
                    f"writes it under a separate acquisition (line {write_line}) "
                    f"— thread roles [{topo.describe(roles)}] can interleave "
                    "between the two regions and the decision goes stale; merge "
                    "the read and the write into one lock region (or re-validate "
                    "before writing)",
                )
            )
        return out
