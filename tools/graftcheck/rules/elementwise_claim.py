"""elementwise-claim: an ``elementwise=True`` kernel must be reduction-free.

The planners (``servable/planner.py``) merge consecutive
``KernelSpec(elementwise=True)`` stages into single XLA programs — the PR 5
fast-path win — and the merge is bit-exact **only because** a reduction-free
graph has no accumulation order for XLA to reorder (the SystemML fusion-plan
lesson, PAPERS.md: plan-validity invariants must be checked, not assumed). A
spec that claims ``elementwise=True`` over a body that actually sums, dots or
sorts would let the merge move hundreds of ulps, silently, on whichever
batches happen to fuse.

The claim is statically checkable because of the shared-body convention
(kernel-spec-consistency): every spec's math comes from ``ops/kernels.py``
``*_fn`` functions. For each ``KernelSpec(elementwise=True)`` construction,
the rule resolves the kernels-module functions the enclosing ``kernel_spec``
references (through the index's import bindings and ``KERNEL_ALIASES``) and
walks their bodies **and their resolved callees within ops/kernels.py**
(nested defs included) for cross-element accumulation primitives:
``sum`` / ``dot`` / ``mean`` / ``einsum`` / ``matmul`` (the ``@`` operator
included) / ``cumsum`` / ``prod`` / ``sort`` / ``argmax`` / ``norm`` and
friends (``index.REDUCTION_PRIMS``).

Reduction-bearing kernels are fine — Normalizer's row norm, DCT's matmul and
the model heads all keep their own programs — they just must not *claim*
elementwise. Unset is always safe, merely unmerged.

Since the precision tier (PR 19, ``servable/precision.py``) the same scope
carries a second claim: **kernel bodies are precision-neutral**. The bf16
tier rounds at program ingest and stage boundaries in the *planner*; a cast
to a sub-f32 dtype inside a kernels-module body (or inside a
``kernel_spec``'s glue) would downcast an accumulator in BOTH partitions —
silently changing f32-tier numerics and voiding the elementwise/merge
claims. :class:`KernelCastBoundaryRule` flags every such cast (the index's
``casts`` fact: ``astype``/``convert_element_type``/``dtype=`` naming
bfloat16/float16/int8 and friends).
"""
from __future__ import annotations

from typing import Dict, List, Set, Tuple

from tools.graftcheck.engine import Finding, Project, Rule, register

KERNELS_REL = "flink_ml_tpu/ops/kernels.py"


@register
class ElementwiseClaimRule(Rule):
    name = "elementwise-claim"
    severity = "error"
    description = (
        "KernelSpec(elementwise=True) bodies (and their resolved ops/kernels "
        "callees) must contain no reduction primitives — the program-merge "
        "bit-exactness contract"
    )

    def run(self, project: Project) -> List[Finding]:
        index = project.index
        kfacts = index.files.get(KERNELS_REL)
        if kfacts is None:
            return []  # fixture trees without a kernels module: nothing to check
        kmodule = kfacts["module"]

        # Reductions per kernels-module function, nested defs folded into
        # their parent, then the transitive closure over resolved calls.
        direct: Dict[str, Set[str]] = {}
        for qual, ff in kfacts["functions"].items():
            owner = qual.split(".<locals>.")[0]
            node = f"{kmodule}:{owner}"
            for prim, line in ff["reductions"]:
                direct.setdefault(node, set()).add(f"{prim}@{line}")
        trans = index.transitive_closure(direct)

        def reductions_of(fn_name: str) -> List[Tuple[str, int]]:
            hits = trans.get(f"{kmodule}:{fn_name}", set())
            out = []
            for h in sorted(hits):
                prim, _, line = h.partition("@")
                out.append((prim, int(line)))
            return out

        findings: List[Finding] = []
        for rel in sorted(index.files):
            f = index.files[rel]
            if not rel.startswith("flink_ml_tpu/"):
                continue
            for ctor in f.get("kspec_ctors", []):
                if not ctor["elementwise"]:
                    continue
                for bound in ctor["kernel_names"]:
                    binding = f["bindings"].get(bound)
                    if binding is None:
                        continue
                    src, orig = binding
                    if src != kmodule or orig not in kfacts["functions"]:
                        continue
                    for prim, line in reductions_of(orig):
                        findings.append(
                            self.finding(
                                rel,
                                ctor["line"],
                                f"KernelSpec(elementwise=True) composes "
                                f"`{orig}` which performs the reduction "
                                f"`{prim}` ({KERNELS_REL}:{line}) — merging "
                                "it would reorder FP accumulation across the "
                                "program boundary; drop elementwise=True or "
                                "split the reduction into its own spec",
                            )
                        )
        return findings


@register
class KernelCastBoundaryRule(Rule):
    name = "kernel-cast-boundary"
    severity = "error"
    description = (
        "no sub-f32 cast inside kernels-module bodies or kernel_spec glue — "
        "the precision tier rounds ONLY at planner stage boundaries"
    )

    def run(self, project: Project) -> List[Finding]:
        index = project.index
        findings: List[Finding] = []
        for rel in sorted(index.files):
            f = index.files[rel]
            if not rel.startswith("flink_ml_tpu/"):
                continue
            in_kernels = rel == KERNELS_REL
            for qual, ff in f["functions"].items():
                # Scope: every kernels-module body (the shared fused-math
                # surface) plus kernel_spec/sparse_kernel_spec glue anywhere
                # (nested defs inherit the spec's qual prefix).
                owner = qual.split(".<locals>.")[0]
                owner_ff = f["functions"].get(owner, ff)
                if not in_kernels and not owner_ff.get("is_kernel_spec"):
                    continue
                for tok, line in ff.get("casts", ()):
                    findings.append(
                        self.finding(
                            rel,
                            line,
                            f"cast to sub-f32 dtype `{tok}` inside "
                            f"{'ops/kernels body' if in_kernels else 'kernel_spec glue'} "
                            f"`{qual}` — kernel math is precision-neutral "
                            "(f32 accumulation); the bf16 tier rounds at "
                            "planner stage boundaries only "
                            "(servable/precision.py). Remove the in-body "
                            "downcast",
                        )
                    )
        return findings
