"""blocking-under-lock: no blocking work inside contended lock regions.

The runtime's locks guard tiny state transitions (queue membership, the
``(version, servable)`` tuple, ledger windows, metric dicts) and sit directly
on request paths: ``submit`` takes the batcher lock per request, every metric
bump takes the registry lock, every admission consults the controller.
Anything *blocking* done while holding one — a sleep, file I/O, an XLA
``.compile()``, a ``device_put`` upload, a thread join, a blocking
queue/future wait — turns every concurrent request into a convoy behind it
(and a multi-second XLA compile under a lock is a p99 cliff, the
swap-off-the-serving-path discipline PR 2/4 exist to prevent).

Until graftcheck v3 the rule was allowlisted to the serving tier; it now
runs whole-program, gated by the inferred thread topology
(``tools/graftcheck/topology.py``): a lock is **contended** when functions
acquiring it span ≥ 2 thread roles, or one multi-instance role (a pool
races with itself). Blocking under an uncontended lock (a module-level init
lock only the main role ever takes) convoys nobody and stays quiet — the
topology, not a path allowlist, decides what is policed.

The rule composes with lock-order's machinery on the shared index: lock
regions come from the same per-file facts (``with self._lock:`` nesting with
``Condition`` aliasing), and blocking reach is transitive over the resolved
call graph — a helper that sleeps three calls down still flags at the call
site made while the lock is held.

Blocking operations (extracted per file by the index):

- ``time.sleep`` (module alias and from-import aware)
- file I/O: ``open``, blocking ``os.*`` / ``shutil.*`` calls
- device/compile work: ``.compile()``, ``jax.device_put``,
  ``block_until_ready``, ``jax.device_get``
- blocking waits: ``.join()`` on ``threading.Thread`` attributes, ``.get()``
  / ``.put()`` on ``queue.Queue`` attributes, ``.wait()`` on
  ``threading.Event`` attributes, ``.result()`` on futures/handles

``Condition.wait`` on the condition of the *held* lock is exempt — it
releases that lock while waiting (the batcher's coalescing window); a wait
against any *other* lock's condition still flags.
"""
from __future__ import annotations

from typing import Dict, List, Set

from tools.graftcheck.engine import Finding, Project, Rule, register
from tools.graftcheck.rules.lock_order import _lock_id
from tools.graftcheck.topology import topology_for

_KIND_LABEL = {
    "sleep": "sleeps",
    "io": "does file I/O",
    "device": "does device/compile work",
    "queue": "blocks on a queue",
    "join": "joins a thread",
    "wait": "waits on an event/condition",
    "future": "blocks on a future result",
}


def contended_locks(project: Project) -> Set[str]:
    """Canonical ids of locks whose acquirers span ≥ 2 thread roles (or one
    multi-instance role) — the locks a second thread can actually wait on."""
    index = project.index
    topo = topology_for(project)
    lock_roles: Dict[str, Set[str]] = {}
    for rel, f in index.files.items():
        module = f["module"]
        for qual, ff in f["functions"].items():
            if not ff["acquires"]:
                continue
            roles = topo.roles_of(f"{module}:{qual}")
            for tok in ff["acquires"]:
                lock_roles.setdefault(_lock_id(module, ff["cls"], tok), set()).update(roles)
    return {
        lock
        for lock, roles in lock_roles.items()
        if len(roles) >= 2 or any(topo.is_multi(r) for r in roles)
    }


@register
class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    severity = "error"
    description = (
        "no blocking work (sleep, file I/O, XLA compile/device_put, queue/"
        "thread/future waits) inside contended lock regions anywhere in the "
        "package, directly or through any resolved call chain"
    )

    def run(self, project: Project) -> List[Finding]:
        index = project.index
        contended = contended_locks(project)

        # Transitive "this callee may block" facts over the whole call graph.
        direct: Dict[str, Set[str]] = {}
        for rel, f in index.files.items():
            module = f["module"]
            for qual, ff in f["functions"].items():
                kinds = {
                    f"{kind}:{detail}" for kind, _line, detail, _held in ff["blocking"]
                }
                if kinds:
                    direct[f"{module}:{qual}"] = kinds
        trans = index.transitive_closure(direct)

        findings: List[Finding] = []
        for rel in sorted(index.files):
            f = index.files[rel]
            module = f["module"]
            for qual in sorted(f["functions"]):
                ff = f["functions"][qual]
                where = f"{module}.{qual}"
                for kind, line, detail, held in ff["blocking"]:
                    lock = self._contended_innermost(module, ff, held, contended)
                    if lock is None:
                        continue
                    findings.append(
                        self.finding(
                            rel,
                            line,
                            f"{where} {_KIND_LABEL[kind]} ({detail}) while "
                            f"holding {lock} — blocking work under a contended "
                            "lock convoys every thread waiting on it; move it "
                            "outside the lock region",
                        )
                    )
                seen: Set[tuple] = set()
                for ref, line, held, _guards in ff["calls"]:
                    lock = self._contended_innermost(module, ff, held, contended)
                    if lock is None:
                        continue
                    callee = index.resolve_ref(module, ff["cls"], qual, ref)
                    if callee is None:
                        continue
                    kinds = trans.get(callee, set())
                    if not kinds:
                        continue
                    if (callee, lock) in seen:
                        continue
                    seen.add((callee, lock))
                    ops = ", ".join(sorted(k.split(":", 1)[1] for k in kinds))
                    findings.append(
                        self.finding(
                            rel,
                            line,
                            f"{where} calls {callee.replace(':', '.')} while "
                            f"holding {lock}, which reaches blocking work "
                            f"({ops}) — hoist the blocking call out of the "
                            "lock region",
                        )
                    )
        return findings

    @staticmethod
    def _contended_innermost(module, ff, held, contended) -> "str | None":
        """The innermost *contended* held lock at a site, or None."""
        for tok in reversed(held):
            lock = _lock_id(module, ff["cls"], tok)
            if lock in contended:
                return lock
        return None
