"""blocking-under-lock: no blocking work inside serving-tier lock regions.

The serving tier's locks guard tiny state transitions (queue membership, the
``(version, servable)`` tuple, metric dicts) and sit directly on the request
path: ``submit`` takes the batcher lock per request, every metric bump takes
the registry lock. Anything *blocking* done while holding one — a sleep, file
I/O, an XLA ``.compile()``, a ``device_put`` upload, a thread join, a
blocking queue/future wait — turns every concurrent request into a convoy
behind it (and a multi-second XLA compile under a lock is a p99 cliff, the
swap-off-the-serving-path discipline PR 2/4 exist to prevent).

The rule composes with lock-order's machinery on the shared index: lock
regions come from the same per-file facts (``with self._lock:`` nesting with
``Condition`` aliasing), and blocking reach is transitive over the resolved
call graph — a helper that sleeps three calls down still flags at the call
site made while the lock is held.

Blocking operations (extracted per file by the index):

- ``time.sleep`` (module alias and from-import aware)
- file I/O: ``open``, blocking ``os.*`` / ``shutil.*`` calls
- device/compile work: ``.compile()``, ``jax.device_put``,
  ``block_until_ready``, ``jax.device_get``
- blocking waits: ``.join()`` on ``threading.Thread`` attributes, ``.get()``
  / ``.put()`` on ``queue.Queue`` attributes, ``.wait()`` on
  ``threading.Event`` attributes, ``.result()`` on futures/handles

``Condition.wait`` on the condition of the *held* lock is exempt — it
releases that lock while waiting (the batcher's coalescing window); a wait
against any *other* lock's condition still flags.
"""
from __future__ import annotations

from typing import Dict, List, Set

from tools.graftcheck.engine import Finding, Project, Rule, register
from tools.graftcheck.rules.lock_order import SCOPE as LOCK_SCOPE, _lock_id

#: Lock regions policed here: the serving tier (lock-order's scope) plus the
#: two fast-path modules whose plans execute next to serving locks.
SCOPE = LOCK_SCOPE + (
    "flink_ml_tpu/servable/planner.py",
    "flink_ml_tpu/builder/batch_plan.py",
)

_KIND_LABEL = {
    "sleep": "sleeps",
    "io": "does file I/O",
    "device": "does device/compile work",
    "queue": "blocks on a queue",
    "join": "joins a thread",
    "wait": "waits on an event/condition",
    "future": "blocks on a future result",
}


@register
class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    severity = "error"
    description = (
        "no blocking work (sleep, file I/O, XLA compile/device_put, queue/"
        "thread/future waits) inside serving-tier lock regions, directly or "
        "through any resolved call chain"
    )

    def run(self, project: Project) -> List[Finding]:
        index = project.index
        in_scope = [
            rel
            for rel in sorted(index.files)
            if any(rel.startswith(p) for p in SCOPE)
        ]

        # Transitive "this callee may block" facts over the whole call graph
        # (direct facts from every file — the finding only fires at a scoped
        # call site made while a lock is held).
        direct: Dict[str, Set[str]] = {}
        for rel, f in index.files.items():
            module = f["module"]
            for qual, ff in f["functions"].items():
                kinds = {
                    f"{kind}:{detail}" for kind, _line, detail, _held in ff["blocking"]
                }
                if kinds:
                    direct[f"{module}:{qual}"] = kinds
        trans = index.transitive_closure(direct)

        findings: List[Finding] = []
        for rel in in_scope:
            f = index.files[rel]
            module = f["module"]
            for qual in sorted(f["functions"]):
                ff = f["functions"][qual]
                where = f"{module}.{qual}"
                for kind, line, detail, held in ff["blocking"]:
                    if not held:
                        continue
                    lock = _lock_id(module, ff["cls"], held[-1])
                    findings.append(
                        self.finding(
                            rel,
                            line,
                            f"{where} {_KIND_LABEL[kind]} ({detail}) while "
                            f"holding {lock} — blocking work under a serving "
                            "lock convoys every concurrent request; move it "
                            "outside the lock region",
                        )
                    )
                seen: Set[tuple] = set()
                for ref, line, held in ff["calls"]:
                    if not held:
                        continue
                    callee = index.resolve_ref(module, ff["cls"], qual, ref)
                    if callee is None:
                        continue
                    kinds = trans.get(callee, set())
                    if not kinds:
                        continue
                    lock = _lock_id(module, ff["cls"], held[-1])
                    if (callee, lock) in seen:
                        continue
                    seen.add((callee, lock))
                    ops = ", ".join(sorted(k.split(":", 1)[1] for k in kinds))
                    findings.append(
                        self.finding(
                            rel,
                            line,
                            f"{where} calls {callee.replace(':', '.')} while "
                            f"holding {lock}, which reaches blocking work "
                            f"({ops}) — hoist the blocking call out of the "
                            "lock region",
                        )
                    )
        return findings
