"""graftcheck engine: rule registry, project model, index/cache, reporting.

The framework is deliberately small: a *rule* is an object with a ``name``,
a default ``severity``, a ``description``, a ``granularity`` and a
``run(project)`` (or, for file-granularity rules, ``check_file(project, sf)``)
method returning :class:`Finding`s. Rules register themselves via
:func:`register`; ``tools.graftcheck.rules`` imports every rule module so
importing the package populates the registry. The engine owns everything
rule-agnostic —

- loading the target tree into :class:`SourceFile`s (path, dotted module
  name, source, content hash) with **lazy** AST parsing — a warm cached run
  never calls ``ast.parse``;
- the **project index** (``tools/graftcheck/index.py``): symbol table,
  resolved import graph, call graph, per-file rule facts — built once per run
  and cached incrementally on disk keyed by file content hash
  (``tools/graftcheck/cache.py``);
- per-file caching of **file-granularity** rule findings (same content-hash
  key, plus the rule's ``cache_version``);
- ``# graftcheck: disable=<rule>[,<rule>...]`` / ``disable=all`` line
  suppressions (same-line only, like ``noqa``), severity overrides,
  JSON/human rendering (SARIF lives in ``sarif.py``), and the exit-code
  contract (non-zero iff an unsuppressed *error*-severity finding exists).
"""
from __future__ import annotations

import ast
import os
import re
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

from tools.graftcheck.index import ProjectIndex, extract_facts

__all__ = [
    "Finding",
    "SourceFile",
    "Project",
    "Rule",
    "REGISTRY",
    "register",
    "run_rules",
    "JSON_SCHEMA_VERSION",
]

JSON_SCHEMA_VERSION = 3  # v3: per-rule wall times + rule granularity; v2: index/cache stats

SEVERITIES = ("error", "warning")


@dataclass(frozen=True)
class Finding:
    """One diagnostic. ``path`` is repo-relative with forward slashes so JSON
    output is stable across platforms; ``line`` is 1-based."""

    rule: str
    path: str
    line: int
    message: str
    severity: str = "error"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.severity}: {self.message}"


@dataclass
class SourceFile:
    path: str  # absolute
    rel: str  # repo-relative, forward slashes
    module: str  # dotted ("flink_ml_tpu.serving.batcher"; packages lose .__init__)
    source: str
    digest: str  # content hash (the cache key)

    _tree: Optional[ast.AST] = field(default=None, repr=False)
    _parsed: bool = False
    parse_error: Optional[tuple] = None  # (line, message) when unparsable
    _suppressions: Optional[Dict[int, Set[str]]] = None

    @property
    def tree(self) -> Optional[ast.AST]:
        """The parsed AST — parsed on first access so cache-warm runs that
        never need it never pay for it. ``None`` when the file has a syntax
        error (recorded in :attr:`parse_error`)."""
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.source, filename=self.path)
            except SyntaxError as e:
                self._tree = None
                self.parse_error = (e.lineno or 1, f"syntax error: {e.msg}")
        return self._tree

    @property
    def suppressions(self) -> Dict[int, Set[str]]:
        if self._suppressions is None:
            self._suppressions = parse_suppressions(self.source)
        return self._suppressions


_SUPPRESS_RE = re.compile(r"#\s*graftcheck:\s*disable=([A-Za-z0-9_\-,\s]+)")


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """line (1-based) -> set of suppressed rule names (or {"all"})."""
    out: Dict[int, Set[str]] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            if rules:
                out[lineno] = rules
    return out


class Project:
    """The analysis targets plus enough repo context for cross-cutting rules
    (fault-points needs ``tests/``; layer-deps needs the module set).

    ``cache`` is an optional :class:`tools.graftcheck.cache.IndexCache`; when
    attached, per-file index facts and file-granularity findings come from /
    go to disk keyed by content hash. The :attr:`index` property materializes
    the whole-program :class:`ProjectIndex` on first access.
    """

    def __init__(self, repo_root: str, targets: Sequence[str], cache=None):
        self.repo_root = os.path.abspath(repo_root)
        self.targets = list(targets)
        self.cache = cache
        self.files: List[SourceFile] = []
        for target in self.targets:
            self._load(os.path.join(self.repo_root, target))
        self.files.sort(key=lambda f: f.rel)
        self._by_rel = {f.rel: f for f in self.files}
        self._facts: Optional[Dict[str, dict]] = None
        self._index: Optional[ProjectIndex] = None
        self.parse_errors: List[Finding] = []

    def _load(self, target: str) -> None:
        if os.path.isfile(target):
            self._load_file(target)
            return
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for name in sorted(filenames):
                if name.endswith(".py"):
                    self._load_file(os.path.join(dirpath, name))

    def _load_file(self, path: str) -> None:
        from tools.graftcheck.cache import content_hash

        rel = os.path.relpath(path, self.repo_root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            source = f.read()
        module = rel[: -len(".py")].replace("/", ".")
        if module.endswith(".__init__"):
            module = module[: -len(".__init__")]
        self.files.append(
            SourceFile(
                path=path, rel=rel, module=module, source=source,
                digest=content_hash(source),
            )
        )

    # -- index / facts ---------------------------------------------------------
    def facts(self) -> Dict[str, dict]:
        """Per-file index facts for every file, from the cache where content
        hashes match, extracted (one AST pass) where they don't. Also fills
        :attr:`parse_errors`."""
        if self._facts is not None:
            return self._facts
        out: Dict[str, dict] = {}
        errors: List[Finding] = []
        for sf in self.files:
            facts = self.cache.get_facts(sf.rel, sf.digest) if self.cache else None
            if facts is None:
                facts = extract_facts(sf.rel, sf.module, sf.source, sf.tree)
                if sf.parse_error is not None:
                    facts["parse_error"] = [sf.parse_error[0], sf.parse_error[1]]
                if self.cache:
                    self.cache.put_facts(sf.rel, sf.digest, facts)
            if facts.get("parse_error"):
                line, msg = facts["parse_error"]
                errors.append(Finding(rule="parse", path=sf.rel, line=line, message=msg))
            out[sf.rel] = facts
        self._facts = out
        self.parse_errors = errors
        return out

    @property
    def index(self) -> ProjectIndex:
        if self._index is None:
            self._index = ProjectIndex(self.facts())
        return self._index

    @property
    def topology(self):
        """The inferred thread topology (``tools/graftcheck/topology.py``) —
        built from the index once per run, shared by every concurrency rule."""
        from tools.graftcheck.topology import topology_for

        return topology_for(self)

    def save_cache(self) -> None:
        if self.cache:
            self.cache.prune(self.repo_root, [f.rel for f in self.files])
            self.cache.save()

    # -- lookups ---------------------------------------------------------------
    def iter_files(self, prefix: Optional[str] = None) -> Iterable[SourceFile]:
        """Files whose repo-relative path starts with ``prefix`` (all if None)."""
        for f in self.files:
            if prefix is None or f.rel.startswith(prefix):
                yield f

    def file(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel.replace(os.sep, "/"))


class Rule:
    """Base class. Subclasses set ``name``/``severity``/``description`` and
    implement ``run`` (project granularity) or ``check_file`` (file
    granularity — findings are cacheable per content hash; bump
    ``cache_version`` whenever the rule's logic changes)."""

    name: str = ""
    severity: str = "error"
    description: str = ""
    granularity: str = "project"  # or "file"
    cache_version: int = 1

    def run(self, project: Project) -> List[Finding]:
        if self.granularity == "file":
            out: List[Finding] = []
            for sf in project.files:
                out.extend(self.check_file(project, sf))
            return out
        raise NotImplementedError  # pragma: no cover - abstract

    def check_file(self, project: Project, sf: SourceFile) -> List[Finding]:
        raise NotImplementedError  # pragma: no cover - abstract

    def finding(self, path: str, line: int, message: str, severity: Optional[str] = None) -> Finding:
        return Finding(
            rule=self.name,
            path=path,
            line=line,
            message=message,
            severity=severity or self.severity,
        )


#: name -> rule instance. Populated by :func:`register` at import time of
#: ``tools.graftcheck.rules``.
REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the registry."""
    rule = cls()
    if not rule.name:
        raise ValueError(f"{cls.__name__} has no rule name")
    if rule.severity not in SEVERITIES:
        raise ValueError(f"{cls.__name__}: bad severity {rule.severity!r}")
    if rule.name in REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    REGISTRY[rule.name] = rule
    return cls


@dataclass
class RunResult:
    findings: List[Finding]  # unsuppressed, sorted
    suppressed: List[Finding]
    files_checked: int
    rules_run: List[str]
    cache_hits: int = 0
    cache_misses: int = 0
    #: rule name -> wall seconds spent in that rule (file rules: summed over
    #: files, cache hits included — the honest CI number).
    rule_times: Dict[str, float] = field(default_factory=dict)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def exit_code(self) -> int:
        return 1 if self.errors else 0

    def restricted_to(self, paths: Set[str]) -> "RunResult":
        """The same run, findings filtered to ``paths`` (the ``--changed-only``
        view: analysis still ran whole-program, only reporting narrows)."""
        return RunResult(
            findings=[f for f in self.findings if f.path in paths],
            suppressed=[f for f in self.suppressed if f.path in paths],
            files_checked=self.files_checked,
            rules_run=self.rules_run,
            cache_hits=self.cache_hits,
            cache_misses=self.cache_misses,
            rule_times=self.rule_times,
        )

    def render_timings(self) -> str:
        """Per-rule wall-time breakdown, slowest first (the CI budget view)."""
        total = sum(self.rule_times.values())
        lines = ["rule                              time     share"]
        for name, secs in sorted(self.rule_times.items(), key=lambda kv: -kv[1]):
            share = (secs / total * 100.0) if total else 0.0
            lines.append(f"{name:<32} {secs * 1000.0:7.1f}ms {share:5.1f}%")
        lines.append(f"{'total':<32} {total * 1000.0:7.1f}ms")
        return "\n".join(lines)

    def to_json(self) -> dict:
        by_rule: Dict[str, int] = {}
        for f in self.findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        return {
            "version": JSON_SCHEMA_VERSION,
            "rules": [
                {
                    "name": REGISTRY[name].name,
                    "severity": REGISTRY[name].severity,
                    "granularity": REGISTRY[name].granularity,
                    "description": REGISTRY[name].description,
                }
                for name in self.rules_run
                if name in REGISTRY
            ],
            "findings": [asdict(f) for f in self.findings],
            "summary": {
                "files_checked": self.files_checked,
                "findings": len(self.findings),
                "errors": len(self.errors),
                "suppressed": len(self.suppressed),
                "by_rule": by_rule,
                "cache": {"hits": self.cache_hits, "misses": self.cache_misses},
                "rule_times_ms": {
                    name: round(secs * 1000.0, 3)
                    for name, secs in sorted(self.rule_times.items())
                },
            },
        }

    def render_human(self) -> str:
        lines = []
        for f in self.findings:
            lines.append(f.render())
        lines.append(
            f"graftcheck: {len(self.findings)} finding(s) "
            f"({len(self.errors)} error(s), {len(self.suppressed)} suppressed) "
            f"across {self.files_checked} file(s), rules: {', '.join(self.rules_run)}"
        )
        return "\n".join(lines)


def _run_file_rule(project: Project, rule: Rule, sf: SourceFile) -> List[Finding]:
    """File-granularity execution with content-hash finding cache."""
    key = f"{rule.name}:{rule.cache_version}"
    if project.cache is not None:
        cached = project.cache.get_findings(sf.rel, sf.digest, key)
        if cached is not None:
            return [Finding(**d) for d in cached]
    found = list(rule.check_file(project, sf))
    if project.cache is not None:
        project.cache.put_findings(sf.rel, sf.digest, key, [asdict(f) for f in found])
    return found


def run_rules(
    project: Project,
    rules: Optional[Sequence[str]] = None,
    severity_overrides: Optional[Dict[str, str]] = None,
) -> RunResult:
    """Run ``rules`` (default: every registered rule, sorted by name) over the
    project, apply suppressions and severity overrides, and sort findings."""
    names = sorted(REGISTRY) if rules is None else list(rules)
    unknown = [n for n in names if n not in REGISTRY]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)} (have: {', '.join(sorted(REGISTRY))})")
    overrides = severity_overrides or {}
    for sev in overrides.values():
        if sev not in SEVERITIES:
            raise ValueError(f"bad severity override {sev!r}")

    project.facts()  # materialize the index facts (and parse errors) once
    raw: List[Finding] = list(project.parse_errors)
    rule_times: Dict[str, float] = {}
    for name in names:
        rule = REGISTRY[name]
        t0 = time.perf_counter()
        if rule.granularity == "file":
            for sf in project.files:
                raw.extend(_run_file_rule(project, rule, sf))
        else:
            raw.extend(rule.run(project))
        rule_times[name] = time.perf_counter() - t0

    processed: List[Finding] = []
    for f in raw:
        sev = overrides.get(f.rule, f.severity)
        if sev != f.severity:
            f = Finding(rule=f.rule, path=f.path, line=f.line, message=f.message, severity=sev)
        processed.append(f)

    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in processed:
        sf = project.file(f.path)
        rules_at_line = sf.suppressions.get(f.line, set()) if sf else set()
        if f.rule in rules_at_line or "all" in rules_at_line:
            suppressed.append(f)
        else:
            kept.append(f)
    key = lambda f: (f.path, f.line, f.rule, f.message)
    cache = project.cache
    return RunResult(
        findings=sorted(kept, key=key),
        suppressed=sorted(suppressed, key=key),
        files_checked=len(project.files),
        rules_run=names,
        cache_hits=cache.hits if cache else 0,
        cache_misses=cache.misses if cache else 0,
        rule_times=rule_times,
    )
