"""graftcheck engine: rule registry, project model, suppressions, reporting.

The framework is deliberately small: a *rule* is an object with a ``name``,
a default ``severity``, a ``description`` and a ``run(project)`` method that
returns :class:`Finding`s. Rules register themselves via :func:`register`;
``tools.graftcheck.rules`` imports every rule module so importing the package
populates the registry. The engine owns everything rule-agnostic —

- parsing the target tree once into :class:`SourceFile`s (path, dotted module
  name, source, AST),
- ``# graftcheck: disable=<rule>[,<rule>...]`` / ``disable=all`` line
  suppressions (same-line only, like ``noqa``),
- severity overrides, JSON/human rendering, and the exit-code contract
  (non-zero iff an unsuppressed *error*-severity finding exists).
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import asdict, dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set

__all__ = [
    "Finding",
    "SourceFile",
    "Project",
    "Rule",
    "REGISTRY",
    "register",
    "run_rules",
    "JSON_SCHEMA_VERSION",
]

JSON_SCHEMA_VERSION = 1

SEVERITIES = ("error", "warning")


@dataclass(frozen=True)
class Finding:
    """One diagnostic. ``path`` is repo-relative with forward slashes so JSON
    output is stable across platforms; ``line`` is 1-based."""

    rule: str
    path: str
    line: int
    message: str
    severity: str = "error"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.severity}: {self.message}"


@dataclass
class SourceFile:
    path: str  # absolute
    rel: str  # repo-relative, forward slashes
    module: str  # dotted ("flink_ml_tpu.serving.batcher"; packages lose .__init__)
    source: str
    tree: ast.AST

    _suppressions: Optional[Dict[int, Set[str]]] = None

    @property
    def suppressions(self) -> Dict[int, Set[str]]:
        if self._suppressions is None:
            self._suppressions = parse_suppressions(self.source)
        return self._suppressions


_SUPPRESS_RE = re.compile(r"#\s*graftcheck:\s*disable=([A-Za-z0-9_\-,\s]+)")


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """line (1-based) -> set of suppressed rule names (or {"all"})."""
    out: Dict[int, Set[str]] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            if rules:
                out[lineno] = rules
    return out


class Project:
    """The parsed analysis targets plus enough repo context for cross-cutting
    rules (fault-points needs ``tests/``; layer-deps needs the module set)."""

    def __init__(self, repo_root: str, targets: Sequence[str]):
        self.repo_root = os.path.abspath(repo_root)
        self.targets = list(targets)
        self.files: List[SourceFile] = []
        self.parse_errors: List[Finding] = []
        for target in self.targets:
            self._load(os.path.join(self.repo_root, target))
        self.files.sort(key=lambda f: f.rel)

    def _load(self, target: str) -> None:
        if os.path.isfile(target):
            self._load_file(target)
            return
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for name in sorted(filenames):
                if name.endswith(".py"):
                    self._load_file(os.path.join(dirpath, name))

    def _load_file(self, path: str) -> None:
        rel = os.path.relpath(path, self.repo_root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            source = f.read()
        module = rel[: -len(".py")].replace("/", ".")
        if module.endswith(".__init__"):
            module = module[: -len(".__init__")]
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_errors.append(
                Finding(
                    rule="parse",
                    path=rel,
                    line=e.lineno or 1,
                    message=f"syntax error: {e.msg}",
                )
            )
            return
        self.files.append(SourceFile(path=path, rel=rel, module=module, source=source, tree=tree))

    def iter_files(self, prefix: Optional[str] = None) -> Iterable[SourceFile]:
        """Files whose repo-relative path starts with ``prefix`` (all if None)."""
        for f in self.files:
            if prefix is None or f.rel.startswith(prefix):
                yield f

    def file(self, rel: str) -> Optional[SourceFile]:
        rel = rel.replace(os.sep, "/")
        for f in self.files:
            if f.rel == rel:
                return f
        return None


class Rule:
    """Base class. Subclasses set ``name``/``severity``/``description`` and
    implement ``run``; most also expose module-level helpers so shims and
    tests can reuse the analysis without the engine."""

    name: str = ""
    severity: str = "error"
    description: str = ""

    def run(self, project: Project) -> List[Finding]:  # pragma: no cover - abstract
        raise NotImplementedError

    def finding(self, path: str, line: int, message: str, severity: Optional[str] = None) -> Finding:
        return Finding(
            rule=self.name,
            path=path,
            line=line,
            message=message,
            severity=severity or self.severity,
        )


#: name -> rule instance. Populated by :func:`register` at import time of
#: ``tools.graftcheck.rules``.
REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the registry."""
    rule = cls()
    if not rule.name:
        raise ValueError(f"{cls.__name__} has no rule name")
    if rule.severity not in SEVERITIES:
        raise ValueError(f"{cls.__name__}: bad severity {rule.severity!r}")
    if rule.name in REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    REGISTRY[rule.name] = rule
    return cls


@dataclass
class RunResult:
    findings: List[Finding]  # unsuppressed, sorted
    suppressed: List[Finding]
    files_checked: int
    rules_run: List[str]

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def exit_code(self) -> int:
        return 1 if self.errors else 0

    def to_json(self) -> dict:
        by_rule: Dict[str, int] = {}
        for f in self.findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        return {
            "version": JSON_SCHEMA_VERSION,
            "rules": [
                {
                    "name": REGISTRY[name].name,
                    "severity": REGISTRY[name].severity,
                    "description": REGISTRY[name].description,
                }
                for name in self.rules_run
                if name in REGISTRY
            ],
            "findings": [asdict(f) for f in self.findings],
            "summary": {
                "files_checked": self.files_checked,
                "findings": len(self.findings),
                "errors": len(self.errors),
                "suppressed": len(self.suppressed),
                "by_rule": by_rule,
            },
        }

    def render_human(self) -> str:
        lines = []
        for f in self.findings:
            lines.append(f.render())
        lines.append(
            f"graftcheck: {len(self.findings)} finding(s) "
            f"({len(self.errors)} error(s), {len(self.suppressed)} suppressed) "
            f"across {self.files_checked} file(s), rules: {', '.join(self.rules_run)}"
        )
        return "\n".join(lines)


def run_rules(
    project: Project,
    rules: Optional[Sequence[str]] = None,
    severity_overrides: Optional[Dict[str, str]] = None,
) -> RunResult:
    """Run ``rules`` (default: every registered rule, sorted by name) over the
    project, apply suppressions and severity overrides, and sort findings."""
    names = sorted(REGISTRY) if rules is None else list(rules)
    unknown = [n for n in names if n not in REGISTRY]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)} (have: {', '.join(sorted(REGISTRY))})")
    overrides = severity_overrides or {}
    for sev in overrides.values():
        if sev not in SEVERITIES:
            raise ValueError(f"bad severity override {sev!r}")

    raw: List[Finding] = list(project.parse_errors)
    for name in names:
        for f in REGISTRY[name].run(project):
            sev = overrides.get(f.rule, f.severity)
            if sev != f.severity:
                f = Finding(rule=f.rule, path=f.path, line=f.line, message=f.message, severity=sev)
            raw.append(f)

    kept: List[Finding] = []
    suppressed: List[Finding] = []
    by_rel = {f.rel: f for f in project.files}
    for f in raw:
        sf = by_rel.get(f.path)
        rules_at_line = sf.suppressions.get(f.line, set()) if sf else set()
        if f.rule in rules_at_line or "all" in rules_at_line:
            suppressed.append(f)
        else:
            kept.append(f)
    key = lambda f: (f.path, f.line, f.rule, f.message)
    return RunResult(
        findings=sorted(kept, key=key),
        suppressed=sorted(suppressed, key=key),
        files_checked=len(project.files),
        rules_run=names,
    )
