"""Incremental on-disk cache for the project index and file-local findings.

The cache is one JSON file (default ``<repo>/.graftcheck/cache.json``) keyed
by **file content hash**: each entry stores the file's extracted index facts
and, per file-granularity rule, its findings. A warm run therefore re-parses
nothing — it hashes sources (cheap), loads facts and local findings straight
from disk, and only the global composition (call-graph resolution, lock-graph
cycles, hot-region traversal) runs fresh. That is what makes
``--changed-only`` and the second-run CI loop sub-second while the full-tree
cold run stays the gate.

Invalidation is entirely content-driven:

- a file edit changes its hash → that file's facts and findings re-extract;
- a facts-schema change bumps ``index.FACTS_VERSION`` → whole cache ignored;
- a rule logic change bumps that rule's ``cache_version`` → only that rule's
  cached findings re-run (facts survive).

Corrupt or unreadable caches are treated as empty — the cache is a pure
accelerator and can never change results.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Any, Dict, List, Optional

from tools.graftcheck.index import FACTS_VERSION

__all__ = ["IndexCache", "content_hash", "default_cache_path"]

CACHE_SCHEMA_VERSION = 1


def content_hash(source: str) -> str:
    return hashlib.sha1(source.encode("utf-8")).hexdigest()


def default_cache_path(repo_root: str) -> str:
    return os.path.join(repo_root, ".graftcheck", "cache.json")


class IndexCache:
    """Load/store per-file facts and per-(file, rule) findings by content hash."""

    def __init__(self, path: str):
        self.path = path
        self._files: Dict[str, Dict[str, Any]] = {}
        self._dirty = False
        self.hits = 0
        self.misses = 0
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                payload = json.load(f)
            if (
                payload.get("schema") == CACHE_SCHEMA_VERSION
                and payload.get("facts_version") == FACTS_VERSION
            ):
                self._files = payload.get("files", {})
        except (OSError, ValueError):
            self._files = {}

    # -- facts -----------------------------------------------------------------
    def get_facts(self, rel: str, digest: str) -> Optional[Dict[str, Any]]:
        entry = self._files.get(rel)
        if entry and entry.get("hash") == digest and "facts" in entry:
            self.hits += 1
            return entry["facts"]
        self.misses += 1
        return None

    def put_facts(self, rel: str, digest: str, facts: Dict[str, Any]) -> None:
        entry = self._files.get(rel)
        if entry is None or entry.get("hash") != digest:
            entry = {"hash": digest, "findings": {}}
            self._files[rel] = entry
        entry["facts"] = facts
        self._dirty = True

    # -- file-local rule findings ----------------------------------------------
    def get_findings(self, rel: str, digest: str, rule_key: str) -> Optional[List[dict]]:
        entry = self._files.get(rel)
        if entry and entry.get("hash") == digest:
            return entry.get("findings", {}).get(rule_key)
        return None

    def put_findings(self, rel: str, digest: str, rule_key: str, findings: List[dict]) -> None:
        entry = self._files.get(rel)
        if entry is None or entry.get("hash") != digest:
            entry = {"hash": digest, "findings": {}}
            self._files[rel] = entry
        entry.setdefault("findings", {})[rule_key] = findings
        self._dirty = True

    def prune(self, repo_root: str, live_rels: List[str]) -> None:
        """Drop entries for files that no longer exist on disk. Entries merely
        outside the current target set survive — a single-file run must not
        evict the full-tree cache (hash checks keep stale entries harmless)."""
        for rel in set(self._files) - set(live_rels):
            if not os.path.exists(os.path.join(repo_root, rel)):
                del self._files[rel]
                self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        payload = {
            "schema": CACHE_SCHEMA_VERSION,
            "facts_version": FACTS_VERSION,
            "files": self._files,
        }
        directory = os.path.dirname(self.path)
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)  # atomic: a reader never sees a partial cache
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self._dirty = False
