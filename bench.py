"""Benchmark entry point — prints ONE JSON line (headline) and writes
``BENCH_DETAIL.json`` with the full suite.

Headline: the BASELINE.json north-star — LogisticRegression steady-state
training throughput (rows consumed by the fused SGD loop per second once the
dataset is HBM-resident) vs a same-semantics single-host CPU numpy baseline
measured in-process (the stand-in for the reference's CPU-TaskManager
cluster; the reference publishes no absolute LR numbers, BASELINE.md).

Suite (all on the real chip, reference harness semantics — wall-clock
throughput like ``BenchmarkUtils.java:132-143``):

- ``logreg``: a Criteo-class dense shape (250k x 256 f32) resident in HBM
  (DeviceDataCache), SGD driven directly. Steady-state rows/s comes from
  differencing two iteration counts — (t(I2) - t(I1)) / (I2 - I1) isolates
  the per-step cost, exactly how per-row cost amortizes over a 1B-row
  stream. One end-to-end Estimator.fit (including ingest) is also recorded.
  The CPU baseline is measured the same marginal way (data already in RAM).
- ``kmeans``: the reference demo config at 10x shape (100k x 10, k=2;
  ``benchmark-demo.json`` KMeans-1 is 10k). Per-iteration time via the same
  differencing; ``vs_reference_cpu`` anchors end-to-end rows/s against the
  reference's illustrative 1,399 rows/s CPU output for the 10k config
  (flink-ml-benchmark/README.md:86-113) — the only reference-anchored number
  that exists.
- ``mlp``: MXU-bound MLP forward inference at serving shapes (batch 4096,
  256-512-512-8, bf16), timed with pipelined dispatch (issue all steps, block
  once) so the tunnel's completion latency is amortized as it would be in a
  serving loop.

Methodology: every workload warms up once so XLA compilation (the analogue
of the reference's one-time JVM/job-graph startup) never lands in a
steady-state metric; timed numbers are medians of 3 runs.
"""
import json
import sys
import time

import numpy as np

_PEAK_FLOPS = {
    # bf16 dense peak per chip; used for MFU. f32 workloads are reported
    # against the same number (conservative).
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
}

_PEAK_HBM_GBPS = {
    # HBM bandwidth per chip — the roofline denominator for the
    # bandwidth-bound workloads (dense LR, KMeans).
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v4": 1228.0,
    "TPU v6 lite": 1640.0,
}


def _marginal_time(total, r1=5, r2=45, samples=3):
    """Median-of-``samples`` marginal cost via rep differencing: ``total(r)``
    runs r reps and returns its wall time (with a scalar fetch as the
    completion barrier). The tunnel adds a large variable fixed overhead per
    measurement, so only the difference of two rep counts is meaningful.
    Shared by every kernel-grade timing in this file — the protocol must not
    drift between entries."""
    total(2)  # warm-up: compile
    times = [max((total(r2) - total(r1)) / (r2 - r1), 1e-9) for _ in range(samples)]
    return sorted(times)[len(times) // 2]


def _median_time(fn, repeats=5):
    # median-of-5: the dev chip is time-shared behind the tunnel and single
    # measurements swing 2-4x under contention (observed: a 36 ms-floor
    # scatter step reading 10 ms); 5 samples keeps the median out of the
    # spikes at a few seconds of extra wall per workload.
    fn()  # warm-up: XLA compile
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def _median_time_spread(fn, repeats=5):
    """Same protocol as :func:`_median_time`, but also returns the min/max
    window so readers of the JSON see the box's noise next to the headline."""
    fn()  # warm-up: XLA compile
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    times.sort()
    median = times[len(times) // 2]
    spread = {
        "min_s": round(times[0], 4),
        "median_s": round(median, 4),
        "max_s": round(times[-1], 4),
        "repeats": repeats,
    }
    return median, spread


def cpu_env() -> dict:
    """The baseline environment record: which CPU, how many cores, how loaded.
    The reference fixes its measurement procedure (BenchmarkUtils.java:132-143);
    this pins the other half — what the baseline actually ran on."""
    model = "unknown"
    try:
        for line in open("/proc/cpuinfo"):
            if line.startswith("model name"):
                model = line.split(":", 1)[1].strip()
                break
    except OSError:
        pass
    try:
        load1 = float(open("/proc/loadavg").read().split()[0])
    except (OSError, ValueError):
        load1 = None
    import os

    return {"cpu_model": model, "cpu_cores": os.cpu_count(), "loadavg_1m": load1}


def pinned_baseline(step_fn, rows_per_call: int, n_runs: int = 5, calls_per_run: int = 3):
    """Best-of-N CPU-baseline protocol: ``n_runs`` independent measurements
    of ``calls_per_run`` steps each on a shared, noisy box; the HEADLINE
    divides by the STRONGEST run (the most conservative ratio for us), and
    the spread is recorded so readers see the noise instead of guessing.
    Returns (best_rows_per_sec, spread_dict)."""
    step_fn()  # warm caches
    rates = []
    for _ in range(n_runs):
        t0 = time.perf_counter()
        for _ in range(calls_per_run):
            step_fn()
        rates.append(calls_per_run * rows_per_call / (time.perf_counter() - t0))
    best = max(rates)
    spread = {
        "best_rows_per_sec": round(best, 1),
        "min_rows_per_sec": round(min(rates), 1),
        "median_rows_per_sec": round(sorted(rates)[len(rates) // 2], 1),
        "n_runs": n_runs,
        "env": cpu_env(),
    }
    return best, spread


def bench_logreg(peak_flops, peak_gbps):
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.iteration import DeviceDataCache
    from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression
    from flink_ml_tpu.ops import SGD, BinaryLogisticLoss
    from flink_ml_tpu.parallel.mesh import get_mesh_context

    n, d = 250_000, 256
    batch = 65_536
    i1, i2 = 100, 2100
    rng = np.random.default_rng(0)
    X = rng.standard_normal(size=(n, d), dtype=np.float32)
    w_true = rng.standard_normal(size=d, dtype=np.float32)
    y = (X @ w_true + 0.5 * rng.standard_normal(size=n, dtype=np.float32) > 0).astype(
        np.float32
    )

    # Steady state: dataset resident in HBM (DeviceDataCache), optimizer driven
    # directly; differencing two iteration counts isolates the per-step cost.
    ctx = get_mesh_context()
    cache = DeviceDataCache(
        {"features": X, "labels": y, "weights": np.ones(n, np.float32)}, ctx=ctx
    )

    def steps(iters):
        SGD(max_iter=iters, global_batch_size=batch, tol=0.0).optimize(
            np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE
        )

    t1 = _median_time(lambda: steps(i1))
    t2 = _median_time(lambda: steps(i2))
    step_s = max((t2 - t1) / (i2 - i1), 1e-9)
    flops_per_step = 4.0 * batch * d  # fwd X@coef (2BD) + grad X.T@mult (2BD)

    # End-to-end: one Estimator.fit including host->device ingest. On this
    # dev box the TPU sits behind a network tunnel (~25 MB/s for random data),
    # so ingest dominates; recorded for honesty, not used as the headline.
    df = DataFrame.from_dict({"features": X, "label": y.astype(np.float64)})
    t0 = time.perf_counter()
    LogisticRegression().set_max_iter(i1).set_global_batch_size(batch).set_tol(0.0).fit(df)
    e2e = time.perf_counter() - t0

    # Roofline: this step is HBM-bound, not FLOP-bound — X is read twice
    # (forward X@coef, gradient X.T@mult; everything else is O(d) or O(B)).
    bytes_per_step = 2.0 * batch * d * 4
    out = {
        "name": "logreg_fit_250k_d256_b65536",
        "steady_rows_per_sec": round(batch / step_s, 1),
        "step_time_us": round(step_s * 1e6, 1),
        "achieved_gflops": round(flops_per_step / step_s / 1e9, 1),
        "achieved_gbps": round(bytes_per_step / step_s / 1e9, 1),
        "peak_hbm_gbps": peak_gbps,
        "e2e_fit_time_s_100_iters": round(e2e, 3),
        "e2e_note": "includes host->device ingest over the dev tunnel (~25 MB/s)",
    }
    if peak_gbps:
        out["hbm_utilization"] = round(bytes_per_step / step_s / 1e9 / peak_gbps, 3)
    if peak_flops:
        out["mfu"] = round(flops_per_step / step_s / peak_flops, 6)
    return out, (X, y)


def bench_logreg_cpu_baseline(X, y, batch=65_536):
    """Same minibatch-SGD semantics in numpy on the host CPU (the stand-in for
    the reference's CPU TaskManager), measured with the pinned best-of-N
    protocol (the same dataset, already resident in RAM)."""
    n, d = X.shape
    coef = np.zeros(d, np.float32)
    offset = 0

    def step():
        nonlocal coef, offset
        Xb, yb = X[offset : offset + batch], y[offset : offset + batch]
        ys = 2.0 * yb - 1.0
        z = (Xb @ coef) * ys
        mult = -ys / (1.0 + np.exp(z))
        grad = Xb.T @ mult
        coef = coef - 0.1 / len(Xb) * grad
        offset = 0 if offset + batch >= n else offset + batch

    return pinned_baseline(step, batch, n_runs=5, calls_per_run=10)


def bench_logreg_sparse(peak_flops, peak_gbps=None):
    """The actual Criteo shape: wide sparse features in padded-CSR layout.

    2^22-dim coefficient, 39 nnz/row (Criteo has 39 feature fields) — a batch
    that would be 1 TB/step densified streams as [B, 40] index/value pairs.
    Steady-state rows/s via the same two-point differencing as the dense
    benchmark.
    """
    from flink_ml_tpu.iteration import DeviceDataCache
    from flink_ml_tpu.ops import SGD, BinaryLogisticLoss
    from flink_ml_tpu.parallel.mesh import get_mesh_context

    n, d, nnz = 250_000, 1 << 22, 39
    K = 40  # lane-padded row width
    batch = 65_536
    i1, i2 = 50, 550
    rng = np.random.default_rng(1)
    idx = rng.integers(0, d, size=(n, K), dtype=np.int32)  # hash-style indices
    vals = np.ones((n, K), np.float32)
    vals[:, nnz:] = 0.0  # padding slots
    w_true = (rng.random(d) < 0.001) * rng.standard_normal(d).astype(np.float32)
    y = (np.sum(vals * w_true[idx], axis=1) > 0).astype(np.float32)

    ctx = get_mesh_context()
    cache = DeviceDataCache(
        {"indices": idx, "values": vals, "labels": y, "weights": np.ones(n, np.float32)},
        ctx=ctx,
    )

    def steps(iters, premat="auto"):
        sgd = SGD(
            max_iter=iters, global_batch_size=batch, tol=0.0,
            learning_rate=0.5, onehot_premat=premat,
        )
        sgd.optimize(np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE)
        return sgd

    premat_active = steps(2).onehot_premat_active  # compile + gate decision
    t1 = _median_time(lambda: steps(i1))
    t2 = _median_time(lambda: steps(i2))
    step_s = max((t2 - t1) / (i2 - i1), 1e-9)
    # The build-form (rebuild-one-hots-every-step) time, for the record:
    # what the same fit costs when the premat one-hots don't fit HBM
    # (many-window/streamed regime) — and the continuity column against
    # rounds 3-4, which measured this form as the headline.
    if premat_active:
        steps(2, premat="off")
        b1 = _median_time(lambda: steps(i1, premat="off"))
        b2 = _median_time(lambda: steps(i2, premat="off"))
        build_step_s = max((b2 - b1) / (i2 - i1), 1e-9)
    else:
        build_step_s = step_s
    # fwd gather-dot (2*B*K) + grad scatter (2*B*K), counting madds like dense
    flops_per_step = 4.0 * batch * K

    # Same-semantics CPU step (gather-dot, np.add.at scatter, full coefficient
    # update, batch-offset cycling), measured with the pinned best-of-N
    # protocol. The TPU side auto-selects the one-hot matmul path
    # (linalg/onehot_sparse.py, Pallas crossings) — the step is
    # crossing-bound; docs/benchmarks.md has the roofline and the multi-chip
    # scaling artifact.
    coef = np.zeros(d, np.float32)
    offset = 0

    def cpu_step():
        nonlocal coef, offset
        Xb_i, Xb_v, yb = (
            idx[offset : offset + batch],
            vals[offset : offset + batch],
            y[offset : offset + batch],
        )
        ys = 2.0 * yb - 1.0
        z = np.sum(Xb_v * coef[Xb_i], axis=1) * ys
        mult = -ys / (1.0 + np.exp(z))
        grad = np.zeros(d, np.float32)
        np.add.at(grad, Xb_i.ravel(), (Xb_v * mult[:, None]).ravel())
        coef = coef - (0.5 / len(yb)) * grad
        offset = 0 if offset + batch >= n else offset + batch

    cpu_best, cpu_spread = pinned_baseline(cpu_step, batch, n_runs=5, calls_per_run=3)

    out = {
        "name": "logreg_sparse_fit_250k_d4M_nnz39_b65536",
        "steady_rows_per_sec": round(batch / step_s, 1),
        "step_time_us": round(step_s * 1e6, 1),
        "achieved_gflops": round(flops_per_step / step_s / 1e9, 2),
        "onehot_premat_active": premat_active,
        "build_form_step_time_us": round(build_step_s * 1e6, 1),
        "vs_build_form": round(build_step_s / step_s, 2),
        "cpu_baseline_rows_per_sec": round(cpu_best, 1),
        "cpu_baseline_spread": cpu_spread,
        "vs_cpu_baseline": round((batch / step_s) / cpu_best, 2),
        "note": "padded-CSR; densified this batch would be ~1 TB/step; "
        "ratio divides by the STRONGEST of 5 baseline runs; the headline "
        "step runs the premat (precomputed-one-hot) kernels when "
        "onehot_premat_active, with build_form_step_time_us the "
        "rebuild-every-step form rounds 3-4 measured",
    }
    if peak_flops:
        out["mfu"] = round(flops_per_step / step_s / peak_flops, 8)
    # The crossing roofline: what the "remaining cost is crossing-bound"
    # claim actually means, in numbers (skipped when auto picked scatter).
    memo = getattr(cache, "_onehot_memo", None)
    if memo is not None and memo[1] is not None:
        from flink_ml_tpu.parallel.mesh import is_tpu_backend

        out.update(
            _crossing_roofline(
                memo[1], out["step_time_us"], peak_flops, peak_gbps,
                use_pallas=is_tpu_backend(ctx.mesh.devices.flat),
                premat=premat_active,
            )
        )
    return out


def _crossing_roofline(lay, step_us, peak_flops, peak_gbps, use_pallas=True, premat=False):
    """Quantified crossing roofline (VERDICT r4 next #3): measure the two
    crossing kernels ALONE at the step's exact unit shapes, and bound them
    by spec — MXU FLOPs at bf16 peak and HBM stream bytes at peak
    bandwidth. Returns fields for the sparse bench entry; derivation in
    docs/benchmarks.md (sparse roofline section).

    The bound is for the crossing *as contracted* (the one-hot matmul's own
    FLOPs/bytes), so crossing_bound_share says how close those kernels run
    to hardware limits, and step_share_crossing says how much of the whole
    step they explain — together they either close the "what remains is
    crossing-bound" claim or size the remaining gap.
    """
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.linalg.onehot_sparse import (
        dot_crossing_pallas,
        dot_crossing_premat_pallas,
        dot_crossing_premat_xla,
        dot_crossing_xla,
        mult_crossing_pallas,
        mult_crossing_premat_pallas,
        mult_crossing_premat_xla,
        mult_crossing_xla,
        premat_row_onehots,
    )

    n_sub, n_flat, sub = lay.n_sub, lay.n_flat, lay.sub_batch
    row_hi = lay.row_hi
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.standard_normal((n_sub, n_flat)).astype(np.float32))
    rhi = jnp.asarray(rng.integers(0, row_hi, (n_sub, n_flat)).astype(np.int32))
    rlo = jnp.asarray(rng.integers(0, 128, (n_sub, n_flat)).astype(np.int32))
    mult3 = jnp.asarray(
        rng.standard_normal((n_sub, row_hi, 128)).astype(np.float32)
    )
    dot_fn = dot_crossing_pallas if use_pallas else dot_crossing_xla
    mult_fn = mult_crossing_pallas if use_pallas else mult_crossing_xla

    @jax.jit
    def both(q, rhi, rlo, mult3):
        d3 = dot_fn(q, rhi, rlo, row_hi)
        u = mult_fn(mult3, rhi, rlo, row_hi)
        return d3, u

    def _time_form(f, *args):
        def total(reps):
            t0 = time.perf_counter()
            for _ in range(reps):
                d3, u = f(*args)
            float(d3[0, 0, 0]) + float(u.reshape(-1)[0])  # fetch barrier
            return time.perf_counter() - t0

        return _marginal_time(total)

    build_s = _time_form(both, q, rhi, rlo, mult3)

    # The premat form at the same unit shape (one window's one-hots,
    # materialized once outside the timed region) — ONLY when the step's
    # gate admitted the path: if it was rejected for not fitting HBM, the
    # roofline must not allocate the very stacks the gate refused.
    if premat:
        rowid = (rhi * 128 + rlo).astype(jnp.int16)
        oh_hi, oh_lo = jax.jit(premat_row_onehots, static_argnums=1)(rowid, row_hi)
        pdot = dot_crossing_premat_pallas if use_pallas else dot_crossing_premat_xla
        pmult = mult_crossing_premat_pallas if use_pallas else mult_crossing_premat_xla

        @jax.jit
        def both_premat(q, mult3, oh_hi, oh_lo):
            return pdot(q, oh_hi, oh_lo), pmult(mult3, oh_hi, oh_lo)

        premat_s = _time_form(both_premat, q, mult3, oh_hi, oh_lo)
        crossing_s = premat_s
    else:
        premat_s = None
        crossing_s = build_s

    # Each crossing: 2 split-bf16 halves x 2 flops/MAC over the
    # [n_flat x (row_hi*128=sub)] one-hot contraction, per sub-batch.
    crossing_flops = 8.0 * n_sub * n_flat * sub
    if premat:
        # Premat form HBM traffic: each crossing re-streams the window's
        # materialized one-hots ((row_hi + 128) bf16 per entry) plus
        # q in / u out; dot3/mult3 are [row_hi, 128] f32 = sub*4 B, small.
        n_pad = oh_hi.shape[-2]
        crossing_bytes = n_sub * (
            2.0 * n_pad * (row_hi + 128) * 2 + 2.0 * n_flat * 4 + 2.0 * sub * 4
        )
    else:
        # Build-form HBM traffic: q/rhi/rlo in, u out (4 B x n_flat each);
        # one-hots are built in VMEM and never touch HBM.
        crossing_bytes = n_sub * (4.0 * n_flat * 4 + 2.0 * sub * 4)
    out = {
        "crossing_only_ms": round(crossing_s * 1e3, 2),
        "crossing_build_form_ms": round(build_s * 1e3, 2),
        "crossing_premat_ms": (
            round(premat_s * 1e3, 2) if premat_s is not None else None
        ),
        "crossing_mxu_bound_ms": (
            round(crossing_flops / peak_flops * 1e3, 2) if peak_flops else None
        ),
        "crossing_hbm_bound_ms": (
            round(crossing_bytes / (peak_gbps * 1e9) * 1e3, 3) if peak_gbps else None
        ),
        "step_share_crossing": round(crossing_s * 1e6 / step_us, 3),
    }
    if peak_flops and peak_gbps:
        bound_s = max(crossing_flops / peak_flops, crossing_bytes / (peak_gbps * 1e9))
        out["crossing_bound_share"] = round(bound_s / crossing_s, 3)
    return out


def bench_onehot_per_chip_sweep(peak_flops):
    """The north-star per-chip shapes, timed on the real chip (VERDICT r4
    next #1): run the fused one-hot program single-chip at the LOCAL shard
    shape of p in {1, 2, 4, 8, 16} data-parallel chips (local batch 65536
    down to 4096, sub tracking the 16384 cap) and record measured step time
    next to the predicted compiled-FLOP falloff — wall-clock evidence for
    (or against) the 1/p^2 crossing-scaling projection that
    tools/crossing_scaling.py derives from cost analysis.

    A p-way DP step is the per-shard program plus one psum; timing the
    per-shard shape on one chip measures everything except the collective,
    which at 16 MB/coef over ICI is sub-ms — the projection's error bar.
    """
    d, nnz, K = 1 << 22, 39, 40
    global_batch = 65_536
    rows = []
    for p in (1, 2, 4, 8, 16):
        try:
            rows.append(_sweep_row(p, global_batch, d, nnz, K))
        except Exception as e:  # a failing shape must not sink the sweep
            rows.append({"p": p, "error": f"{type(e).__name__}: {str(e)[:300]}"})
    ok = [r for r in rows if "error" not in r]
    # Falloff columns are anchored at p=1 by definition; if that row failed,
    # rebasing silently would make every falloff read ~p_base x too small.
    base = ok[0] if ok and ok[0]["p"] == 1 else None
    if base is None and ok:
        for r in ok:
            r["falloff_note"] = "p=1 row missing: falloff columns omitted"
    if base is not None:
        for r in ok:
            r["predicted_flop_falloff"] = round(
                base["predicted_flops_per_chip"] / r["predicted_flops_per_chip"], 2
            )
            r["measured_time_falloff"] = round(
                base["measured_step_ms"] / r["measured_step_ms"], 2
            )
            if peak_flops:
                r["mfu"] = round(
                    r["predicted_flops_per_chip"]
                    / (r["measured_step_ms"] / 1e3)
                    / peak_flops,
                    4,
                )
    return {
        "name": "onehot_per_chip_shape_sweep",
        "global_batch": global_batch,
        "dim": d,
        "nnz": nnz,
        "rows": rows,
        "note": "single-chip wall-clock at each p's per-shard shape; "
        "measured_time_falloff is the hardware-evidence column for the "
        "crossing-scaling projection (predicted_flop_falloff); excludes "
        "the per-step psum (sub-ms at 16 MB over ICI). Deltas have a "
        "400-iteration floor (a contention-shrunk pilot once produced an "
        "unusable flat sweep); the time-shared chip still swings single "
        "rows 2-4x, so cross-run BANDS (BASELINE.md) are the quotable "
        "numbers, not any one run's row",
    }


def _sweep_row(p, global_batch, d, nnz, K):
    """One p's per-shard measurement (see bench_onehot_per_chip_sweep)."""
    from flink_ml_tpu.iteration import DeviceDataCache
    from flink_ml_tpu.linalg.onehot_sparse import BLOCK
    from flink_ml_tpu.ops import SGD, BinaryLogisticLoss

    lb = global_batch // p
    rng = np.random.default_rng(100 + p)
    idx = rng.integers(0, d, size=(lb, K), dtype=np.int32)
    vals = np.ones((lb, K), np.float32)
    vals[:, nnz:] = 0.0
    y = (rng.random(lb) > 0.5).astype(np.float32)
    cache = DeviceDataCache(
        {
            "indices": idx,
            "values": vals,
            "labels": y,
            "weights": np.ones(lb, np.float32),
        }
    )

    def steps(iters):
        sgd = SGD(
            max_iter=iters, global_batch_size=lb, tol=0.0,
            learning_rate=0.5, sparse_kernel="onehot",
        )
        sgd.optimize(np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE)
        return sgd

    # Pilot differencing to size the real delta: the marginal estimate
    # must itself be a difference (a single-point pilot is ~all fixed
    # ~1 s tunnel dispatch overhead at small shards). The final delta is
    # sized to ~5 s of pure step time, a multiple of that overhead, with
    # a 400-iteration floor — a contention spike during the pilot must
    # not shrink the real delta into the noise (observed: a sweep row
    # reading 7 ms where the headline's pinned protocol reads 12-17).
    premat_active = steps(2).onehot_premat_active  # compile + gate decision
    p1 = _median_time(lambda: steps(5), repeats=3)
    p2 = _median_time(lambda: steps(55), repeats=3)
    est_step = max((p2 - p1) / 50, 2e-4)
    extra = int(min(max(400, 5.0 / est_step), 5000))
    i1, i2 = 10, 10 + extra
    t1 = _median_time(lambda: steps(i1))
    t2 = _median_time(lambda: steps(i2))
    step_ms = max((t2 - t1) / (i2 - i1), 1e-9) * 1e3

    lay = cache._onehot_memo[1]
    flops = 4.0 * lay.n_sub * lay.n_flat * (lay.sub_batch + 2 * BLOCK)
    return {
        "p": p,
        "local_batch": lb,
        "sub_batch": lay.sub_batch,
        "n_sub": lay.n_sub,
        "n_flat": lay.n_flat,
        "onehot_premat_active": premat_active,
        "predicted_flops_per_chip": flops,
        "measured_step_ms": round(step_ms, 2),
    }


def bench_logreg_sparse_streamed():
    """The north-star rehearsal: every Criteo ingredient run TOGETHER —
    streamed (larger-than-HBM windows out of a spilling host cache) + sparse
    (padded-CSR) + fused — now on the ONE-HOT matmul kernel (the streamed
    path auto-selects it since round 4; windows share one compiled program
    through the global OneHotSparsePlan).

    Row count is scaled to the dev tunnel (~25 MB/s host->device): the
    machinery is what's under test; per-row cost is shape-invariant. Three
    numbers matter: the streamed one-hot step time (must be comparable to
    the resident path's), the scatter step it replaced, and the overlap
    efficiency — the fraction of compute the prefetch actually hides behind
    ingest (wall ≈ ingest when overlap is perfect and ingest dominates).
    """
    import tempfile

    from flink_ml_tpu.iteration import DeviceDataCache, HostDataCache
    from flink_ml_tpu.ops import SGD, BinaryLogisticLoss

    n, d, nnz = 250_000, 1 << 22, 39
    K = 40
    batch = 65_536
    epochs = 8
    window = 125_000
    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as spill:
        cache = HostDataCache(memory_budget_bytes=64 << 20, spill_dir=spill)
        for lo in range(0, n, 25_000):  # the synthetic Criteo-shaped stream
            m = min(25_000, n - lo)
            idx = rng.integers(0, d, size=(m, K), dtype=np.int32)
            vals = np.ones((m, K), np.float32)
            vals[:, nnz:] = 0.0
            cache.append(
                {
                    "indices": idx,
                    "values": vals,
                    "labels": (rng.random(m) > 0.5).astype(np.float32),
                    "weights": np.ones(m, np.float32),
                }
            )
        cache.finish()

        last_fit = {}

        def streamed_fit(kernel):
            sgd = SGD(
                max_iter=epochs,
                global_batch_size=batch,
                tol=0.0,
                learning_rate=0.5,
                stream_window_rows=window,
                sparse_kernel=kernel,
            )
            t0 = time.perf_counter()
            sgd.optimize(np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE)
            last_fit["premat"] = sgd.onehot_premat_active
            return time.perf_counter() - t0

        streamed_fit("scatter")  # warm-up: program compile
        wall_scatter = streamed_fit("scatter")
        streamed_fit("onehot")  # warm-up: plan + program compile

        # Pure-ingest time: load the windows the run actually loads (dedup
        # consecutive same-window runs — run_windows keeps those resident),
        # no compute. Measured IMMEDIATELY BEFORE the timed fit — the tunnel
        # drifts 20-40% between measurements, so probe and fit must be
        # adjacent — and the counting pass the fit repeats is timed
        # separately and removed from wall for the overlap accounting — it
        # is neither ingest nor compute, and runs before any window exists.
        from flink_ml_tpu.iteration.streaming import WindowSchedule
        from flink_ml_tpu.linalg.onehot_sparse import BLOCK, SUB_ROWS
        from flink_ml_tpu.ops.optimizer import _OneHotWindowStream, streamed_onehot_plan
        from flink_ml_tpu.parallel.mesh import get_mesh_context

        ctx = get_mesh_context()
        m_shard = -(-n // ctx.n_data)
        b_local = -(-batch // ctx.n_data)
        sub = min(SUB_ROWS, b_local)
        W = WindowSchedule(m_shard, b_local, window, epochs).window
        t0 = time.perf_counter()
        plan = streamed_onehot_plan(cache, n, ctx.n_data, W, b_local, d)
        plan_s = time.perf_counter() - t0
        n_sub = -(-b_local // sub)
        flops = 4.0 * n_sub * plan.n_flat * (sub + 2 * BLOCK)
        sched = WindowSchedule(
            m_shard, b_local, window, epochs, flops_per_epoch=flops
        )
        # The probe must exercise the SAME load() path the fit uses — with
        # premat engaged, load() also materializes the window's one-hots on
        # device, and that cost belongs to the probe's ingest_s, not to the
        # overlap formula's residual.
        stream = _OneHotWindowStream(
            cache, ctx, plan, sched.window, b_local, n_sub, m_shard, n,
            premat=last_fit.get("premat", False),
        )
        visited = [j for j, _ in sched.runs]
        loads = [j for i, j in enumerate(visited) if i == 0 or j != visited[i - 1]]
        t0 = time.perf_counter()
        for j in loads:
            import jax

            buf = stream.load(j)
            jax.block_until_ready(buf.get("oh", buf["labels"]))
        ingest_s = time.perf_counter() - t0
        del buf

        wall = streamed_fit("onehot")

    # The compute half, measured directly: the one-hot program on a
    # window-sized resident cache — the VERDICT's "comparable to the
    # resident path" criterion, plus the scatter step it replaced.
    rng2 = np.random.default_rng(8)
    widx = rng2.integers(0, d, size=(window, K), dtype=np.int32)
    wvals = np.ones((window, K), np.float32)
    wvals[:, nnz:] = 0.0
    wcache = DeviceDataCache(
        {
            "indices": widx,
            "values": wvals,
            "labels": (rng2.random(window) > 0.5).astype(np.float32),
            "weights": np.ones(window, np.float32),
        }
    )

    def wsteps(kernel, iters):
        SGD(
            max_iter=iters, global_batch_size=batch, tol=0.0, learning_rate=0.5,
            sparse_kernel=kernel,
        ).optimize(np.zeros(d, np.float32), wcache, BinaryLogisticLoss.INSTANCE)

    step_us = {}
    for kernel in ("onehot", "scatter"):
        # 100-step differencing: the tunnel's fixed dispatch+fetch overhead
        # is ~1 s with ±0.5 s jitter, so the step-time signal must be a
        # multiple of that (30 steps of a ~22 ms step was not; observed
        # extractions from 2.6 to 65 ms for the same kernel).
        t1 = _median_time(lambda: wsteps(kernel, 10))
        t2 = _median_time(lambda: wsteps(kernel, 110))
        step_us[kernel] = max((t2 - t1) / 100, 1e-9) * 1e6

    compute_s = epochs * step_us["onehot"] / 1e6
    wall_train = max(wall - plan_s, 1e-9)  # windows-phase wall: counting pass excluded
    # The probe and the fit cross the tunnel minutes apart at ~25 MB/s with
    # 20-40% drift, so the estimated shares are clamped into [0, 1] — the
    # qualitative conclusion (ingest-bound; compute fully hidden) is robust,
    # the third digit is not.
    ingest_clamped = min(ingest_s, wall_train)
    # Report overlap unmeasured (null) rather than fabricated when either
    # input is outside the measurement's validity: compute below the
    # tunnel's multi-second drift noise, or the probe's ingest exceeding the
    # fit's whole wall (drift between the two runs — clamping it into the
    # formula would emit a deterministic fake 1.0). The tunnel-free CPU-mesh
    # artifact carries the real overlap demonstration.
    if compute_s < 0.05 * wall_train or ingest_s > wall_train:
        overlap = None
    else:
        overlap = (compute_s + ingest_clamped - wall_train) / max(
            min(compute_s, ingest_clamped), 1e-9
        )
        overlap = round(min(max(overlap, 0.0), 1.0), 3)
    rows_consumed = epochs * batch
    return {
        "name": "logreg_sparse_streamed_250k_d4M_w125k",
        "wall_time_s": round(wall, 2),
        "wall_time_s_scatter": round(wall_scatter, 2),
        "plan_pass_s": round(plan_s, 2),
        "epochs": epochs,
        "window_rows": window,
        "e2e_rows_per_sec": round(rows_consumed / wall, 1),
        "onehot_premat_active": last_fit.get("premat", False),
        "onehot_step_us": round(step_us["onehot"], 1),
        "scatter_step_us": round(step_us["scatter"], 1),
        "onehot_vs_scatter_step": round(step_us["scatter"] / step_us["onehot"], 2),
        "ingest_s": round(ingest_s, 2),
        "compute_s": round(compute_s, 2),
        "compute_share": round(min(compute_s / wall_train, 1.0), 4),
        "ingest_share": round(ingest_clamped / wall_train, 4),
        "overlap_efficiency": overlap,
        "note": "streamed+sparse+fused on the one-hot kernel; windows re-cross "
        "the dev tunnel every epoch (~25 MB/s) so wall is ingest-bound here — "
        "overlap_efficiency (fraction of compute hidden behind ingest) is null "
        "when compute sits below the tunnel's drift noise floor; see "
        "streamed_overlap_cpu_mesh for the tunnel-free overlap artifact",
    }


def bench_streamed_overlap_cpu_mesh():
    """Run tools/bench_streamed_overlap.py in a tunnel-free subprocess on the
    8-device virtual CPU mesh (see that module's docstring — the dev tunnel
    makes overlap unmeasurable on the real chip from this box)."""
    import os
    import subprocess

    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": (
                env.get("XLA_FLAGS", "")
                + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=30"
                + " --xla_cpu_collective_call_terminate_timeout_seconds=120"
                + " --xla_force_host_platform_device_count=8"
            ).strip(),
            "PYTHONPATH": os.path.dirname(os.path.abspath(__file__)),
        }
    )
    try:
        proc = subprocess.run(
            [sys.executable, "tools/bench_streamed_overlap.py"],
            capture_output=True, text=True, timeout=1200, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # never sink the whole bench for the side artifact
        return {
            "name": "streamed_overlap_cpu_mesh_196k_d256k",
            "error": f"{type(e).__name__}: {e}",
        }


def bench_mlp_train(peak_flops):
    """Compute-bound training: can the framework feed the MXU?

    The MLPClassifier fused training path (adam, psum, minibatch windows — the
    exact ``fit`` program) at MXU-saturating shapes: batch 32768, layers
    2048-4096-4096-1024, bf16 matmuls (``computeType`` mixed precision). Data
    is generated on device, so the tunnel never touches the measurement; the
    timed unit is one fused multi-epoch dispatch, like a real training run.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from flink_ml_tpu.models.classification.mlp_classifier import (
        MLPClassifier,
        _init_params,
    )
    from flink_ml_tpu.ops.optimizer import offset_schedule
    from flink_ml_tpu.parallel.mesh import get_mesh_context

    n = batch = 32_768
    dims = [2048, 4096, 4096, 1024]
    ctx = get_mesh_context()

    key = jax.random.PRNGKey(0)
    kx, ky = jax.random.split(key)
    X = jax.device_put(jax.random.normal(kx, (n, dims[0]), jnp.float32), ctx.batch)
    y = jax.device_put(
        jax.random.randint(ky, (n,), 0, dims[-1]).astype(jnp.float32), ctx.batch
    )
    w = jax.device_put(jnp.ones(n, jnp.float32), ctx.batch)

    clf = (
        MLPClassifier()
        .set_hidden_layers(*dims[1:-1])
        .set_learning_rate(1e-3)
        .set_global_batch_size(batch)
        .set_tol(0.0)
        .set_compute_type("bfloat16")
    )
    local_batch = max(1, batch // ctx.n_data)
    optimizer = optax.adam(1e-3)
    params = [tuple(jnp.asarray(a) for a in layer) for layer in _init_params(np.random.default_rng(0), dims)]
    opt_state = optimizer.init(params)
    done = ctx.replicate(np.asarray(False))

    epochs = 20
    fused = clf._build_fused(ctx, optimizer, local_batch, epochs, None)
    starts, offsets = offset_schedule(n // ctx.n_data, local_batch, epochs)
    active = np.ones(epochs, bool)

    def run():
        nonlocal params, opt_state, done
        params, opt_state, done, n_exec = fused(
            params, opt_state, done, starts, offsets, active, X, y, w
        )
        jax.block_until_ready(n_exec)

    step_s = _median_time(run) / epochs
    # fwd 2 + bwd 4 madd-flops per weight per row
    flops_per_step = 6.0 * batch * sum(a * b for a, b in zip(dims[:-1], dims[1:]))
    achieved = flops_per_step / step_s
    out = {
        "name": "mlp_train_bf16_b32768_2048_4096_4096_1024",
        "rows_per_sec": round(batch / step_s, 1),
        "step_time_us": round(step_s * 1e6, 1),
        "achieved_tflops": round(achieved / 1e12, 2),
        "note": "full training step: fwd+bwd+psum+adam, the MLPClassifier.fit program",
    }
    if peak_flops:
        out["mfu"] = round(achieved / peak_flops, 4)
    return out


def bench_attention(peak_flops):
    """Long-context attention: the ring fold at a single-chip shape.

    T=8192 causal self-attention (H=4, D=128) through the ring program —
    on one chip that is one fold, which runs as the fused Pallas flash
    kernel (parallel/flash.py): scores never touch HBM. The jnp fold is
    timed alongside so the artifact records the kernel's margin.
    """
    import jax

    from flink_ml_tpu.parallel.mesh import get_mesh_context
    from flink_ml_tpu.parallel.ring import _sharded_program

    from flink_ml_tpu.parallel.flash import flash_available

    rng = np.random.default_rng(3)
    ctx = get_mesh_context()
    B, T, H, D = 1, 8192, 4, 128
    if not flash_available(T // ctx.n_data, D, list(ctx.mesh.devices.flat)):
        return {
            "name": "ring_attention_causal_T8192_h4_d128",
            "note": "flash fold unavailable on this backend/shape; skipped",
        }
    q = jax.device_put(rng.standard_normal((B, T, H, D)).astype(np.float32))
    k = jax.device_put(rng.standard_normal((B, T, H, D)).astype(np.float32))
    v = jax.device_put(rng.standard_normal((B, T, H, D)).astype(np.float32))

    def timed(flash):
        prog = _sharded_program(ctx.mesh, True, False, flash)

        def total(reps):
            t0 = time.perf_counter()
            for _ in range(reps):
                out = prog(q, k, v)
            # fetching a scalar is the reliable completion barrier over the
            # dev tunnel (block_until_ready can resolve on the handle early)
            float(out[0, 0, 0, 0])
            return time.perf_counter() - t0

        return _marginal_time(total)

    t_flash, t_jnp = timed(True), timed(False)
    flops = 4.0 * B * H * T * T * D  # qk^T + pv matmuls (f32, causal-masked)
    out = {
        "name": "ring_attention_causal_T8192_h4_d128",
        "flash_step_ms": round(t_flash * 1e3, 2),
        "jnp_step_ms": round(t_jnp * 1e3, 2),
        "flash_speedup": round(t_jnp / t_flash, 2),
        "achieved_tflops": round(flops / t_flash / 1e12, 2),
        "note": "fused Pallas fold (scores stay in VMEM) vs the jnp fold",
    }
    if peak_flops:
        out["mfu"] = round(flops / t_flash / peak_flops, 4)
    return out


def _attention_train_step_ms(B, T, flash):
    """Time one SelfAttentionClassifier training step (fwd+bwd+psum+adam) —
    the exact ``_train_step`` program ``fit`` compiles — chaining
    params/opt_state through reps (buffer donation) with a scalar fetch as
    the completion barrier and rep differencing (tunnel discipline)."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.models.classification.attention_classifier import (
        _init_params,
        _train_step,
    )
    from flink_ml_tpu.parallel.mesh import DATA_AXIS, get_mesh_context

    ctx = get_mesh_context()
    H, E, vocab, C = 4, 512, 1024, 8  # head dim 128
    rng = np.random.default_rng(5)
    tok = rng.integers(0, vocab, size=(B, T)).astype(np.int32)
    y = rng.integers(0, C, size=(B,)).astype(np.int32)
    params = jax.tree_util.tree_map(jnp.asarray, _init_params(rng, vocab, E, C))
    optimizer, step = _train_step(ctx.mesh, H, 1e-3, flash)
    opt_state = optimizer.init(params)
    tok_dev = jax.device_put(tok, ctx.sharding(None, DATA_AXIS))
    y_dev = ctx.replicate(y)
    w_dev = ctx.replicate(np.ones(B, np.float32))
    nv = jnp.asarray(T, jnp.int32)
    state = {"params": params, "opt": opt_state}

    def total(reps):
        t0 = time.perf_counter()
        for _ in range(reps):
            state["params"], state["opt"], loss = step(
                state["params"], state["opt"], tok_dev, y_dev, w_dev, nv
            )
        float(loss)  # scalar fetch = the reliable barrier over the tunnel
        return time.perf_counter() - t0

    return _marginal_time(total) * 1e3


def _attention_train_flops(B, T, H=4, E=512, C=8):
    # fwd attention 4BHT^2D (qk^T + pv), bwd ~2x more; projections
    # (q/k/v/o/cls) 2 madd-flops fwd + 4 bwd per weight per row.
    return 12.0 * B * H * T * T * (E // H) + 6.0 * B * T * (4 * E * E + E * C)


def bench_attention_train(peak_flops):
    """The SelfAttentionClassifier *fit step* — fwd + bwd + psum + adam —
    the number a user of the SP stage actually gets (VERDICT r4 missing #4
    pinned the fused-fold forward but not the training step).

    Two rows: (a) T=8192 single-chip with the kernel the product gate
    actually picks there — the fused backward's pallas outputs exceed the
    scoped-VMEM training envelope at B*H*T*(D+2)*4 ≈ 17 MB, so fit trains
    on the jnp fold; and (b) the fused training step at B=1, T=4096 — the
    per-shard shape of T=8192 on a 2-chip SP mesh, i.e. the per-chip
    evidence for multi-chip fused training (flash_train_available admits it
    once the sequence axis is sharded).
    """
    from flink_ml_tpu.parallel.flash import flash_available, flash_train_available
    from flink_ml_tpu.parallel.mesh import get_mesh_context

    ctx = get_mesh_context()
    H, E = 4, 512
    if not flash_available(8192 // ctx.n_data, E // H, list(ctx.mesh.devices.flat)):
        return {
            "name": "attention_train_T8192_h4_d128",
            "note": "flash fold unavailable on this backend; skipped",
        }

    out = {"name": "attention_train_T8192_h4_d128", "rows": []}
    for label, B, T in (("fit_T8192_single_chip", 1, 8192), ("fused_per_shard_T4096", 1, 4096)):
        flash = flash_train_available(
            T // ctx.n_data, E // H, B, H, list(ctx.mesh.devices.flat)
        )
        step_ms = _attention_train_step_ms(B, T, flash)
        flops = _attention_train_flops(B, T)
        achieved = flops / (step_ms / 1e3)
        row = {
            "config": label,
            "batch": B,
            "T": T,
            "kernel": "fused" if flash else "jnp_fold",
            "step_time_ms": round(step_ms, 2),
            "tokens_per_sec": round(B * T / (step_ms / 1e3), 1),
            "achieved_tflops": round(achieved / 1e12, 2),
        }
        if peak_flops:
            row["mfu"] = round(achieved / peak_flops, 4)
        out["rows"].append(row)
    out["note"] = (
        "full fit step (fwd+bwd+psum+adam). Single-chip T=8192 trains on the "
        "jnp fold (the fused backward's outputs exceed the scoped-VMEM "
        "training envelope, flash.flash_train_available); the T=4096 row is "
        "the fused per-shard program a 2-chip SP mesh runs for T=8192"
    )
    return out


def bench_kmeans(peak_gbps):
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.models.clustering.kmeans import KMeans

    rng = np.random.default_rng(2)
    num_rows, dim = 100_000, 10
    # wide spread: the per-iteration delta must clear the tunnel's multi-ms
    # dispatch jitter (epochs are ~20 us each once fused)
    i1, i2 = 20, 10_020
    df = DataFrame.from_dict({"features": rng.random((num_rows, dim))})

    def fit(iters):
        KMeans().set_seed(2).set_max_iter(iters).fit(df)

    t1 = _median_time(lambda: fit(i1))
    t2 = _median_time(lambda: fit(i2))
    # A non-positive delta means jitter swamped the measurement — report null
    # rather than a fabricated clamp value.
    iter_s = (t2 - t1) / (i2 - i1) if t2 > t1 else None

    # The reference's own config (10k rows) for the apples-to-apples anchor —
    # rows/s is not shape-invariant, so the 1,399 rows/s comparison uses the
    # exact shape it was measured on.
    df10k = DataFrame.from_dict({"features": rng.random((10_000, dim))})
    t10k = _median_time(lambda: KMeans().set_seed(2).set_max_iter(i1).fit(df10k))
    # Roofline: the fused iteration reads X for distances and again for the
    # centroid update. An achieved number above HBM peak means the 4 MB
    # dataset went VMEM-resident across the scan — report it as-is with the
    # denominator so the comparison stays honest.
    bytes_per_iter = 2.0 * num_rows * dim * 4  # f32 features (KMeans casts)
    out = {
        "name": "kmeans_fit_d10_k2",
        "iter_time_us_100k": None if iter_s is None else round(iter_s * 1e6, 1),
        "e2e_rows_per_sec_100k_20_iters": round(num_rows / t1, 1),
        "fit_time_s_100k_20_iters": round(t1, 3),
        "e2e_rows_per_sec_10k_20_iters": round(10_000 / t10k, 1),
        # reference illustrative CPU output for this exact 10k config (rows/s)
        "reference_cpu_rows_per_sec": 1399.0,
        "vs_reference_cpu_10k": round(10_000 / t10k / 1399.0, 2),
        "peak_hbm_gbps": peak_gbps,
    }
    if iter_s is not None:
        gbps = round(bytes_per_iter / iter_s / 1e9, 1)
        if peak_gbps and gbps > peak_gbps:
            # The 4 MB dataset went VMEM-resident across the fused scan, so
            # HBM peak is the wrong denominator for this entry — report the
            # number under its own key so no table row exceeds 100% of a
            # stated peak (the bytes are HBM-equivalent traffic the scan
            # never actually paid).
            out["vmem_resident_hbm_equiv_gbps"] = gbps
            out["roofline_note"] = (
                "dataset VMEM-resident across the fused scan: the iteration "
                "re-reads X from VMEM, so HBM bandwidth is not the ceiling "
                "and no HBM utilization is claimed; vmem_resident_hbm_equiv_"
                "gbps is the HBM traffic an un-fused iteration would have paid"
            )
        else:
            out["achieved_gbps"] = gbps
            if peak_gbps:
                out["hbm_utilization"] = round(gbps / peak_gbps, 3)
    return out


def bench_training_weak_scaling():
    """Weak-scaling sweep of the sharded training tier
    (docs/distributed_training.md): per-shard work held FIXED while
    ``train.mesh`` sweeps 1/2/4/8, so ideal scaling is flat epoch time and
    linearly growing rows/s. Two legs: the sharded KMeans epoch (mapreduce
    centroid update) and the deterministic-tier SGD step.

    Honest-1-core-box note: on the CI host the 8 "devices" are XLA virtual
    CPU devices time-sharing one core, so epoch time grows ~linearly with
    width instead of holding flat — the sweep here is an overhead/regression
    gate (deal + collective cost at each width, bit-identity priced in),
    not a scaling demonstration; the flat-epoch claim needs >= width cores
    or real chips.
    """
    import jax

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.models.clustering.kmeans import KMeans
    from flink_ml_tpu.ops import SGD, BinaryLogisticLoss
    from flink_ml_tpu.parallel import TrainSharding

    widths = [w for w in (1, 2, 4, 8) if w <= len(jax.devices())]
    rng = np.random.default_rng(5)
    rows_per_shard, dim = 8_192, 8
    i1, i2 = 3, 23

    out = {
        "name": "training_weak_scaling",
        "rows_per_shard": rows_per_shard,
        "dim": dim,
        "note": (
            "weak scaling: per-shard rows fixed, total rows = width x "
            "per-shard; measured on XLA virtual CPU devices time-sharing "
            "one core, so per-epoch time is an overhead gate, not a "
            "scaling demo (see docstring)"
        ),
        "kmeans_epoch": {},
        "sgd_step": {},
    }
    for w in widths:
        n = rows_per_shard * w
        df = DataFrame.from_dict({"features": rng.random((n, dim))})
        config.set(Options.TRAIN_MESH, w)
        try:
            def fit(iters):
                KMeans().set_seed(2).set_k(4).set_max_iter(iters).fit(df)

            t1 = _median_time(lambda: fit(i1), repeats=3)
            t2 = _median_time(lambda: fit(i2), repeats=3)
            epoch_s = (t2 - t1) / (i2 - i1) if t2 > t1 else None
        finally:
            config.unset(Options.TRAIN_MESH)
        out["kmeans_epoch"][f"mesh_{w}"] = {
            "total_rows": n,
            "epoch_p50_ms": None if epoch_s is None else round(epoch_s * 1e3, 3),
            "rows_per_sec": None if epoch_s is None else round(n / epoch_s, 1),
        }

    sgd_batch = 64 * 8  # one quantum multiple at every width
    for w in widths:
        n = rows_per_shard * w
        X = rng.normal(size=(n, dim)).astype(np.float32)
        y = (X.sum(axis=1) > 0).astype(np.float32)
        data = {"features": X, "labels": y}
        ts = TrainSharding(w)

        def opt(iters):
            SGD(
                max_iter=iters,
                learning_rate=0.1,
                global_batch_size=sgd_batch,
                tol=0.0,
                sharding=ts,
            ).optimize(np.zeros(dim), data, BinaryLogisticLoss.INSTANCE)

        t1 = _median_time(lambda: opt(i1), repeats=3)
        t2 = _median_time(lambda: opt(i2), repeats=3)
        step_s = (t2 - t1) / (i2 - i1) if t2 > t1 else None
        out["sgd_step"][f"mesh_{w}"] = {
            "total_rows": n,
            "global_batch": sgd_batch,
            "step_p50_ms": None if step_s is None else round(step_s * 1e3, 3),
            "rows_per_sec": None if step_s is None else round(sgd_batch / step_s, 1),
        }
    return out


def bench_serving():
    """Offered-load sweep over the online serving runtime (docs/serving.md).

    Request sizes 1/8/64 rows — the bucket shapes the micro-batcher pads to —
    each driven from 4 client threads at saturation against a d=256 logistic
    servable (the BASELINE.json CTR shape). Reports throughput (rows/s
    through the full queue→batch→pad→transform→slice path) and p50/p99
    request latency scraped from the server's own ``ml.serving.*`` histogram,
    so BENCH rounds track the serving pillar with the same metrics a
    deployment would alert on. Warmup happens once per bucket at server
    construction (the hot-swap warm path), so compiles never land in the
    timed window — the same discipline as every other workload here.
    """
    import threading

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable import PipelineModelServable
    from flink_ml_tpu.servable.lib import (
        LogisticRegressionModelServable,
        StandardScalerModelServable,
    )
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    rng = np.random.default_rng(5)
    dim = 256
    X = rng.standard_normal((4096, dim)).astype(np.float32)

    def make_lr(features_col="features"):
        servable = LogisticRegressionModelServable().set_features_col(features_col)
        servable.coefficient = rng.standard_normal(dim).astype(np.float32)
        return servable

    def make_pipeline():
        """Depth-2 pipeline: scaler -> logistic, the fusion benchmark shape."""
        scaler = (
            StandardScalerModelServable()
            .set_input_col("features")
            .set_output_col("scaled")
            .set_with_mean(True)
        )
        scaler.mean = rng.standard_normal(dim).astype(np.float32)
        scaler.std = (np.abs(rng.standard_normal(dim)) + 0.5).astype(np.float32)
        return PipelineModelServable([scaler, make_lr("scaled")])

    n_threads = 4
    requests_per_thread = 150

    def run_load(servable, name, req_rows, *, fastpath=None, pipeline_depth=None):
        """Drive the server at saturation from n_threads clients; report
        throughput + p50/p99 from the server's own ml.serving histogram."""
        server = InferenceServer(
            servable,
            name=name,
            serving_config=ServingConfig(
                max_batch_size=64,
                max_delay_ms=1.0,
                queue_capacity_rows=8192,
                default_timeout_ms=120_000,
                fastpath=fastpath,
                pipeline_depth=pipeline_depth,
            ),
            warmup_template=DataFrame.from_dict({"features": X[:1]}),
        )
        try:
            barrier = threading.Barrier(n_threads + 1)

            def client(tid):
                barrier.wait()
                for i in range(requests_per_thread):
                    j = (tid * 997 + i * 61) % (X.shape[0] - req_rows)
                    server.predict(
                        DataFrame.from_dict({"features": X[j : j + req_rows]})
                    )

            threads = [
                threading.Thread(target=client, args=(t,)) for t in range(n_threads)
            ]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            scraped = metrics.scope(server.scope)
            lat = scraped[MLMetrics.SERVING_LATENCY_MS]
            total_rows = n_threads * requests_per_thread * req_rows
            batches = scraped[MLMetrics.SERVING_BATCHES]
            return {
                "request_rows": req_rows,
                "rows_per_sec": round(total_rows / elapsed, 1),
                "requests_per_sec": round(
                    n_threads * requests_per_thread / elapsed, 1
                ),
                "latency_p50_ms": round(lat.quantile(0.5), 3),
                "latency_p99_ms": round(lat.quantile(0.99), 3),
                "mean_batch_rows": round(total_rows / batches, 1),
                "batches": batches,
                "fused_batches": scraped.get(MLMetrics.SERVING_FUSED_BATCHES, 0),
                "warmup_compile_ms": round(
                    scraped.get(MLMetrics.SERVING_WARMUP_COMPILE_MS, 0.0), 1
                ),
            }
        finally:
            server.close()

    sweep = [
        run_load(make_lr(), f"bench-load-{req_rows}", req_rows)
        for req_rows in (1, 8, 64)
    ]

    # Fused-vs-unfused + pipeline-depth sweep on the depth-2 pipeline: the
    # fast-path acceptance contract is a p50 win for fastpath on at depth>=2
    # (fused executable + device-resident weights + pipelined dispatch) over
    # the per-stage transform path on the same pipeline.
    fused_sweep = []
    for fastpath, depth in ((False, 1), (True, 1), (True, 2), (True, 3)):
        leg = run_load(
            make_pipeline(),
            f"bench-fused-{int(fastpath)}-d{depth}",
            8,
            fastpath=fastpath,
            pipeline_depth=depth,
        )
        leg.update({"fastpath": fastpath, "pipeline_depth": depth})
        fused_sweep.append(leg)

    return {
        "name": "serving_microbatch_lr_d256",
        "threads": n_threads,
        "requests_per_thread": requests_per_thread,
        "max_batch_size": 64,
        "sweep": sweep,
        "fused_sweep": fused_sweep,
        "note": "end-to-end serving path (queue + micro-batch + pad + jit'd "
        "transform + slice); latency is enqueue->response per request from "
        "the ml.serving latency histogram. fused_sweep: depth-2 "
        "scaler->logistic pipeline, per-stage transform path (fastpath "
        "false) vs ONE fused AOT executable per bucket with device-resident "
        "weights, at dispatch windows 1-3",
    }


def bench_serving_open_loop():
    """Open-loop offered-load ramp x priority mix (docs/serving.md "Load
    shedding & adaptive control") — the serving number that closed-loop
    sweeps structurally cannot show.

    Every other serving row here is closed-loop: each client thread waits
    for its response before sending again, so the offered rate silently
    adapts to capacity and queueing collapse is invisible. This row drives
    the d=256 logistic servable with flink_ml_tpu.loadgen: seeded Poisson
    arrivals with a heavy-tailed (Zipf) size mix and a 70/30
    guaranteed/best-effort priority split, stepped to ~0.5x / 1x / 2x of a
    measured saturation estimate. Per step: achieved rows/s, p50/p99/p999
    latency, sheds, hard rejects, deadline misses and time-to-first-shed —
    the numbers a capacity plan is actually made of.
    """
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.loadgen import OpenLoopLoadGenerator, ZipfSizes, ramp_schedule
    from flink_ml_tpu.servable.lib import LogisticRegressionModelServable
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    rng = np.random.default_rng(29)
    dim = 256
    X = rng.standard_normal((4096, dim)).astype(np.float32)

    def make_server(name):
        servable = LogisticRegressionModelServable().set_features_col("features")
        servable.coefficient = rng.standard_normal(dim).astype(np.float32)
        return InferenceServer(
            servable,
            name=name,
            serving_config=ServingConfig(
                max_batch_size=64,
                max_delay_ms=1.0,
                queue_capacity_rows=1024,
                default_timeout_ms=30_000,
                shed_sustain_ms=10.0,
            ),
            warmup_template=DataFrame.from_dict({"features": X[:1]}),
        )

    def request(rows):
        j = int(rng.integers(0, X.shape[0] - rows))
        return DataFrame.from_dict({"features": X[j : j + rows]})

    sizes = ZipfSizes((1, 2, 4, 8, 16, 32), alpha=1.5)

    # Calibration: a short deliberately-overloaded burst; the achieved
    # (completed) rows/s under it is the saturation estimate the ramp is
    # expressed against.
    cal_server = make_server("bench-ol-cal")
    try:
        cal_sched = ramp_schedule(
            [(4000.0, 1.0)], sizes=sizes, seed=1, priority_mix={0: 1.0}
        )
        cal_gen = OpenLoopLoadGenerator(cal_sched, request, timeout_ms=30_000.0)
        cal_report = cal_gen.run(cal_server)
        completed_rows = sum(
            s.offered_rows * (s.completed / max(s.arrivals, 1)) for s in cal_report.steps
        )
        saturation_rows_per_s = max(completed_rows / cal_report.wall_s, 1.0)
    finally:
        cal_server.close()
    sat_rps = saturation_rows_per_s / sizes.mean_rows

    server = make_server("bench-ol")
    try:
        steps = [(0.5 * sat_rps, 1.5), (1.0 * sat_rps, 1.5), (2.0 * sat_rps, 1.5)]
        sched = ramp_schedule(
            steps, sizes=sizes, priority_mix={0: 0.7, 1: 0.3}, seed=2
        )
        gen = OpenLoopLoadGenerator(
            sched, request, timeout_ms={0: 30_000.0, 1: 250.0}
        )
        report = gen.run(server)
        controller = server.controller
        sweep = []
        for s in report.steps:
            d = s.as_dict()
            d["offered_x_saturation"] = round(
                s.offered_rps * sizes.mean_rows / saturation_rows_per_s, 2
            )
            # achieved rows/s: the completed fraction of the step's offered rows
            d["achieved_rows_per_sec"] = round(
                s.offered_rows * (s.completed / max(s.arrivals, 1)) / max(s.duration_s, 1e-9),
                1,
            )
            sweep.append(d)
        actions = [
            {"kind": a.kind, "value": a.value, "reason": a.reason}
            for a in controller.actions
            if a.kind in ("depth", "bucket", "mesh.recommend", "shed")
        ][:16]
    finally:
        server.close()

    return {
        "name": "serving_open_loop_lr_d256",
        "saturation_rows_per_sec": round(saturation_rows_per_s, 1),
        "mean_request_rows": round(sizes.mean_rows, 3),
        "priority_mix": {"0": 0.7, "1": 0.3},
        "timeout_ms": {"0": 30000, "1": 250},
        "sweep": sweep,
        "controller_actions": actions,
        "fully_resolved": report.fully_resolved(),
        "note": "open-loop seeded Poisson ramp (flink_ml_tpu.loadgen) against "
        "the d=256 logistic fast path on a 1-core CPU host: absolute rows/s "
        "measures this box's XLA-CPU dispatch, not TPU serving capacity — "
        "the row exists for the SHAPE of the curve (p99/p999 blow-up past "
        "saturation, time-to-first-shed, shed-before-reject ordering, "
        "priority discipline under 2x overload), which is hardware-relative.",
    }


def bench_mlp_serving_throughput():
    """Throughput-mode MLP serving (VERDICT r6 item 8): the batched,
    weight-resident counterpart of ``mlp_forward``'s 0.0135-MFU latency shape.

    Same 256->512->512->8 network, served end-to-end through the
    InferenceServer at batched request sizes (64 rows, coalescing onto a
    256-row max bucket) from 4 client threads at saturation — so the number
    includes queueing, micro-batching, padding and readback, not just the
    matmuls. The fastpath leg keeps every layer's weights device-resident
    (one upload at swap) and serves one fused AOT program per bucket; the
    per-stage leg re-uploads weights per call — the throughput delta IS the
    weight-residency + AOT win. The same network architecture reproduces from
    the CLI alone via the JSON suite
    (``python -m flink_ml_tpu.benchmark flink_ml_tpu/benchmark/configs/
    mlpclassifier-benchmark.json``).
    """
    import threading

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable.lib import MLPClassifierModelServable
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    rng = np.random.default_rng(17)
    dims = (256, 512, 512, 8)
    servable = MLPClassifierModelServable()
    arrays = {"labels": np.arange(dims[-1], dtype=np.float64)}
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        arrays[f"W{i}"] = (
            rng.normal(size=(d_in, d_out)) * np.sqrt(2.0 / d_in)
        ).astype(np.float32)
        arrays[f"b{i}"] = np.zeros(d_out, np.float32)
    X = rng.standard_normal((8192, dims[0])).astype(np.float32)

    n_threads = 4
    requests_per_thread = 60
    req_rows = 64

    def run_leg(fastpath):
        leg_servable = MLPClassifierModelServable()._apply_model_arrays(arrays)
        server = InferenceServer(
            leg_servable,
            name=f"bench-mlp-throughput-{int(fastpath)}",
            serving_config=ServingConfig(
                max_batch_size=256,
                max_delay_ms=1.0,
                queue_capacity_rows=16384,
                default_timeout_ms=120_000,
                fastpath=fastpath,
                pipeline_depth=2,
            ),
            warmup_template=DataFrame.from_dict({"features": X[:1]}),
        )
        try:
            barrier = threading.Barrier(n_threads + 1)

            def client(tid):
                barrier.wait()
                for i in range(requests_per_thread):
                    j = (tid * 997 + i * 193) % (X.shape[0] - req_rows)
                    server.predict(
                        DataFrame.from_dict({"features": X[j : j + req_rows]})
                    )

            threads = [
                threading.Thread(target=client, args=(t,)) for t in range(n_threads)
            ]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            scraped = metrics.scope(server.scope)
            lat = scraped[MLMetrics.SERVING_LATENCY_MS]
            total_rows = n_threads * requests_per_thread * req_rows
            return {
                "fastpath": fastpath,
                "rows_per_sec": round(total_rows / elapsed, 1),
                "latency_p50_ms": round(lat.quantile(0.5), 3),
                "latency_p99_ms": round(lat.quantile(0.99), 3),
                "mean_batch_rows": round(
                    total_rows / scraped[MLMetrics.SERVING_BATCHES], 1
                ),
                "fused_batches": scraped.get(MLMetrics.SERVING_FUSED_BATCHES, 0),
                "fastpath_compiles_post_warmup": scraped.get(
                    MLMetrics.SERVING_FASTPATH_COMPILES, 0
                ),
            }
        finally:
            server.close()

    legs = [run_leg(False), run_leg(True)]
    fused, per_stage = legs[1]["rows_per_sec"], legs[0]["rows_per_sec"]
    return {
        "name": "mlp_serving_throughput_b64_256_512_512_8",
        "threads": n_threads,
        "requests_per_thread": requests_per_thread,
        "request_rows": req_rows,
        "max_batch_size": 256,
        "legs": legs,
        "fused_vs_per_stage": round(fused / per_stage, 2) if per_stage else None,
        "note": "throughput counterpart of mlp_forward's latency shape: "
        "batched 64-row requests through the full serving path; fastpath leg "
        "= device-resident weights + one fused AOT program per bucket, "
        "per-stage leg re-uploads weights per call. Config-suite twin: "
        "mlpclassifier-benchmark.json trains/transforms the same network "
        "from the CLI.",
    }


def bench_continuous_loop():
    """Continuous learning loop (docs/continuous.md): the closed train →
    publish → AOT-warm → flip cycle at the Criteo-ish d=256 online-LR shape.

    What the row quantifies is the loop's *model logistics* cost: the
    publish→serve latency per version (save + poll + plan build + per-bucket
    AOT warm + atomic flip — the window in which the fleet serves the
    previous version), the pre-flip warm time itself, and the goodput
    fraction (productive train/serve time over total, the ML Productivity
    Goodput accounting). Serving-path compiles must be zero: every flip is
    warmed before activation.
    """
    import tempfile

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.linalg.vectors import DenseVector
    from flink_ml_tpu.loop import ContinuousLearningLoop, ContinuousTrainer, DriftMonitor
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.models.classification.online_logistic_regression import (
        OnlineLogisticRegression,
    )
    from flink_ml_tpu.models.online import QueueBatchStream
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    dim = 256
    rng = np.random.default_rng(23)
    true_w = rng.normal(size=dim) / np.sqrt(dim)

    def batch(n=4096, seed=0):
        r = np.random.default_rng(seed)
        X = r.normal(size=(n, dim))
        y = (X @ true_w > 0).astype(np.float64)
        return {"features": X.astype(np.float64), "label": y}

    n_versions = 6
    with tempfile.TemporaryDirectory() as tmp:
        scope = f"{MLMetrics.LOOP_GROUP}[bench]"
        stream = QueueBatchStream()
        for i in range(n_versions):
            stream.add(batch(seed=i))
        trainer = ContinuousTrainer(
            OnlineLogisticRegression()
            .set_initial_model_data(
                DataFrame(["coefficient"], None, [[DenseVector(np.zeros(dim))]])
            )
            .set_alpha(0.5)
            .set_global_batch_size(4096),
            stream,
            tmp + "/pub",
            publish_every_versions=1,
            scope=scope,
        )
        server = InferenceServer(
            name="bench-loop",
            serving_config=ServingConfig(max_batch_size=64, max_delay_ms=0.5),
            warmup_template=DataFrame.from_dict(
                {"features": batch(1, seed=99)["features"]}
            ),
        )
        loop = ContinuousLearningLoop(
            trainer,
            server,
            eval_source=lambda: DataFrame.from_dict(batch(64, seed=77)),
            name="bench",
            monitor=DriftMonitor(window=4, scope=scope),
        )
        t0 = time.perf_counter()
        loop.run(publish_target=n_versions, max_steps=n_versions + 2)
        elapsed = time.perf_counter() - t0
        scraped = metrics.scope(scope)
        hist = scraped[MLMetrics.LOOP_PUBLISH_TO_SERVE_MS]
        result = {
            "name": f"continuous_loop_lr_d{dim}",
            "versions_published": scraped[MLMetrics.LOOP_PUBLISHED],
            "versions_swapped": scraped[MLMetrics.LOOP_SWAPPED],
            "publish_to_serve_p50_ms": round(hist.quantile(0.5), 2),
            "publish_to_serve_p99_ms": round(hist.quantile(0.99), 2),
            "warm_ms_last": round(scraped[MLMetrics.LOOP_WARM_MS], 2),
            "goodput_fraction": round(scraped[MLMetrics.LOOP_GOODPUT_FRACTION], 4),
            "versions_per_sec": round(n_versions / elapsed, 2),
            "serving_path_compiles": metrics.get(
                server.scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0
            ),
            "note": "closed train->publish->warm->flip loop; "
            "publish_to_serve is the stale-model window per version (save + "
            "poll + plan build + per-bucket AOT warm + atomic flip), "
            "goodput_fraction = productive/(productive+overhead) per the ML "
            "Productivity Goodput accounting; serving_path_compiles must be 0",
        }
        server.close()
        return result


def _make_feature6_stages(rng, d, n_docs=400_000):
    """The benched 6-stage feature chain (scaler → normalizer → weighting
    product → idf → rescale → binarizer) — shared by the fusion sweep and
    the cold-start bench so both rows name the same chain."""
    from flink_ml_tpu.models.feature.binarizer import Binarizer
    from flink_ml_tpu.models.feature.elementwise_product import ElementwiseProduct
    from flink_ml_tpu.models.feature.idf import IDFModel
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standard_scaler import StandardScalerModel

    scaler = StandardScalerModel().set_input_col("input").set_output_col("scaled")
    scaler.set_with_mean(True)
    scaler.mean = rng.standard_normal(d)
    scaler.std = np.abs(rng.standard_normal(d)) + 0.5
    idf = IDFModel().set_input_col("weighted").set_output_col("tfidf")
    idf.idf = np.abs(rng.standard_normal(d)) + 0.2
    idf.doc_freq = np.ones(d)
    idf.num_docs = np.asarray(float(n_docs))
    rescale = StandardScalerModel().set_input_col("tfidf").set_output_col("rescaled")
    rescale.set_with_mean(False)
    rescale.mean = np.zeros(d)
    rescale.std = np.abs(rng.standard_normal(d)) + 0.5
    return [
        scaler,
        Normalizer().set_input_col("scaled").set_output_col("norm"),
        ElementwiseProduct()
        .set_scaling_vec(np.abs(rng.standard_normal(d)) + 0.1)
        .set_input_col("norm")
        .set_output_col("weighted"),
        idf,
        rescale,
        Binarizer()
        .set_input_cols("rescaled")
        .set_output_cols("bin")
        .set_thresholds(0.05),
    ]


def bench_cold_start():
    """Persistent compiled-plan cache (docs/plancache.md): publish→first-
    response wall on the 6-stage feature chain + logistic head, three legs
    per fusion tier:

    - **cold cache** — a fresh plan-cache directory: every (program, bucket)
      pays trace + XLA compile + serialize/store. The pre-PR-14 restart cost
      plus the one-time store tax.
    - **warm cache** — a new "incarnation" (fresh servable/plan/server
      objects — fresh jit closures, so nothing rides the in-process jit
      cache) over the populated directory: every program loads its
      serialized executable; compiles drop to zero
      (``ml.plancache.misses`` asserted unchanged).
    - **in-process warm** — the same server again: the steady-state request
      path, for scale.

    Honest 1-core-box note: on this CPU backend the warm leg still pays
    tracing/lowering per program (the digest is the lowered StableHLO — see
    docs/plancache.md), so the win is the compile term only; on real TPUs
    the compile term is 10-100× larger and the ratio grows with it. The
    fast+mega tier reports whether interpret-mode megakernel executables
    serialized or fell back to live compiles (store_errors).
    """
    import os
    import shutil
    import tempfile

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable.builder import PipelineModelServable
    from flink_ml_tpu.servable.fusion import FusionTier
    from flink_ml_tpu.servable.lib import LogisticRegressionModelServable
    from flink_ml_tpu.serving import InferenceServer, ServingConfig
    from flink_ml_tpu.serving.plan import CompiledServingPlan

    d = 32
    max_batch = 16
    rng = np.random.default_rng(31)
    template = DataFrame.from_dict({"input": rng.standard_normal((1, d))})
    request = DataFrame.from_dict({"input": rng.standard_normal((max_batch, d))})

    def make_servable():
        stage_rng = np.random.default_rng(77)
        lr = LogisticRegressionModelServable().set_features_col("bin")
        lr.coefficient = stage_rng.standard_normal(d)
        return PipelineModelServable(_make_feature6_stages(stage_rng, d) + [lr])

    def leg(name, fusion, repeats=3):
        """One (cold, warm, steady) measurement set for a fusion tier."""
        colds, warms = [], []
        pc_scope = MLMetrics.PLANCACHE_GROUP
        cold_stores = warm_miss = store_errors = 0
        base_dir = tempfile.mkdtemp(prefix=f"bench-plancache-{name}-")
        steady_ms = []
        try:
            for r in range(repeats):
                # A fresh, never-seen directory per repeat: the cold leg
                # must start from an empty cache every time.
                config.set(Options.PLANCACHE_DIR, os.path.join(base_dir, f"r{r}"))

                def first_response(tag):
                    t0 = time.perf_counter()
                    server = InferenceServer(
                        make_servable(),
                        name=f"bench-cold-{name}-{tag}",
                        serving_config=ServingConfig(
                            max_batch_size=max_batch,
                            max_delay_ms=0.1,
                            fusion_mode=fusion.mode if fusion else None,
                        ),
                        warmup_template=template,
                    )
                    server.predict(request)
                    wall = time.perf_counter() - t0
                    return server, wall

                if fusion is not None:
                    config.set(Options.FUSION_MEGAKERNEL_MIN_SCORE, 1.0)
                e0 = metrics.get(pc_scope, MLMetrics.PLANCACHE_STORE_ERRORS, 0)
                s0 = metrics.get(pc_scope, MLMetrics.PLANCACHE_STORES, 0)
                server, cold_s = first_response(f"c{r}")
                colds.append(cold_s)
                cold_stores = metrics.get(pc_scope, MLMetrics.PLANCACHE_STORES, 0) - s0
                store_errors = metrics.get(pc_scope, MLMetrics.PLANCACHE_STORE_ERRORS, 0) - e0
                server.close()
                m0 = metrics.get(pc_scope, MLMetrics.PLANCACHE_MISSES, 0)
                server, warm_s = first_response(f"w{r}")
                warms.append(warm_s)
                warm_miss = metrics.get(pc_scope, MLMetrics.PLANCACHE_MISSES, 0) - m0
                if r == repeats - 1:
                    for _ in range(20):
                        t0 = time.perf_counter()
                        server.predict(request)
                        steady_ms.append((time.perf_counter() - t0) * 1000.0)
                server.close()
        finally:
            config.unset(Options.PLANCACHE_DIR)
            config.unset(Options.FUSION_MEGAKERNEL_MIN_SCORE)
            shutil.rmtree(base_dir, ignore_errors=True)
        cold = sorted(colds)[len(colds) // 2]
        warm = sorted(warms)[len(warms) // 2]
        return {
            "cold_publish_to_first_response_s": round(cold, 3),
            "warm_publish_to_first_response_s": round(warm, 3),
            "in_process_warm_p50_ms": round(sorted(steady_ms)[len(steady_ms) // 2], 3),
            "speedup_warm_vs_cold": round(cold / warm, 2),
            "cold_stores": cold_stores,
            "warm_live_compiles": warm_miss,
            "store_errors": store_errors,
        }

    exact = leg("exact", None)
    mega = leg("mega", FusionTier("fast", megakernel=True, min_score=1.0))
    mega["note"] = (
        "interpret-mode Pallas megakernel executables "
        + (
            "serialized and resumed from cache"
            if mega["store_errors"] == 0 and mega["warm_live_compiles"] == 0
            else f"fell back to live compiles for {max(mega['store_errors'], mega['warm_live_compiles'])} program(s)"
        )
    )
    return {
        "name": "cold_start_feature6_logistic",
        "chain": "6-stage feature chain + logistic head, d=32, buckets 1..16",
        "exact": exact,
        "fast_mega": mega,
        "note": "publish->first-response wall per leg (server build + plan "
        "build + per-bucket AOT warm + first request). warm = fresh "
        "servable/plan/server objects over a populated plancache.dir (fresh "
        "jit closures — nothing rides the in-process jit cache); "
        "warm_live_compiles must be 0. 1-core-box note: the warm leg still "
        "pays per-program trace/lowering (the digest is the lowered "
        "StableHLO), so the ratio here prices the XLA-compile term only — "
        "it grows with compile cost on real accelerators.",
    }


def bench_pipeline_batch_transform():
    """Batch transform fast path (docs/batch_transform.md): fused chunked
    CompiledBatchPlan vs the per-stage transform path on a 6-stage feature
    chain (scaler → normalizer → weighting product → idf → rescale →
    binarizer), 400k x 32 (columns several times last-level cache, so both legs run at DRAM bandwidth and the fused plan's ~2x traffic advantage is what the ratio measures).

    The per-stage path pays, per stage: a host gather + f64 astype of its
    input column, a jit dispatch, a blocking ``np.asarray`` readback and a
    full host DataFrame materialization. The fused plan pays one ingest + one
    readback per chunk with columns staying device-resident across all six
    stages (the five elementwise stages merge into reduction-free XLA
    programs; the normalizer's row-norm reduction keeps its own), and
    overlaps chunk j+1's host ingest with chunk j's execution
    (``batch.prefetch.depth``). Reports rows/s for both legs plus a
    chunk-rows × prefetch-depth sweep with p50 per-chunk latency from the
    plan's own ``ml.batch.fastpath`` histogram.

    On a single-core host the whole bench runs with synchronous CPU dispatch
    (restored on exit): the async dispatch thread buys no overlap with one
    core — both legs block on every readback anyway — and its context
    switches tax the fused path's many short program calls 30-40%.
    """
    import os

    import jax

    if (os.cpu_count() or 1) == 1:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
        try:
            return _bench_pipeline_batch_transform_body()
        finally:
            jax.config.update("jax_cpu_enable_async_dispatch", True)
    return _bench_pipeline_batch_transform_body()


def _bench_pipeline_batch_transform_body():
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.builder.batch_plan import CompiledBatchPlan
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.metrics import MLMetrics, metrics

    rng = np.random.default_rng(9)
    n, d = 400_000, 32
    df = DataFrame.from_dict({"input": rng.standard_normal((n, d))})

    # Same rng draw order as the old inline construction — identical params.
    stages = _make_feature6_stages(rng, d, n_docs=n)

    def run_per_stage():
        out = df
        for stage in stages:
            out = stage.transform(out)
        return out

    def fused_leg(chunk_rows, depth, scope):
        config.set(Options.BATCH_CHUNK_ROWS, chunk_rows)
        config.set(Options.BATCH_PREFETCH_DEPTH, depth)
        try:
            plan = CompiledBatchPlan.build(stages, scope=scope)
            plan.transform(df)  # warm: compiles both chunk signatures
            t, spread = _median_time_spread(lambda: plan.transform(df), repeats=3)
            hist = metrics.get(scope, MLMetrics.BATCH_CHUNK_MS)
            return {
                "chunk_rows": chunk_rows,
                "prefetch_depth": depth,
                "rows_per_sec": round(n / t, 1),
                "spread": spread,
                "chunk_p50_ms": round(hist.quantile(0.5), 3) if hist else None,
                "compiles": metrics.get(scope, MLMetrics.BATCH_COMPILES, 0),
            }
        finally:
            config.unset(Options.BATCH_CHUNK_ROWS)
            config.unset(Options.BATCH_PREFETCH_DEPTH)

    # Headline: per-stage vs fused at the config DEFAULTS. The box is
    # time-shared and ambient load swings wall time 3x on a ~100 ms sample,
    # so the protocol is interleaved best-of-N: alternate the legs (so load
    # bursts hit both) and take each leg's MINIMUM — the run with the least
    # interference, the best estimate of true cost on a noisy host (the
    # pyperf min protocol). Medians are reported alongside for honesty.
    plan = CompiledBatchPlan.build(stages, scope="ml.batch[bench-main]")
    for _ in range(2):  # warm twice: jit caches + chunk signatures on the
        run_per_stage()  # first pass, allocator/arena steady state on the
        plan.transform(df)  # second (first-call-after-compile runs ~20% cold)
    ps_times, fu_times = [], []
    for _ in range(9):
        t0 = time.perf_counter()
        run_per_stage()
        ps_times.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        plan.transform(df)
        fu_times.append(time.perf_counter() - t0)
    ps_times.sort()
    fu_times.sort()
    t_ps, t_fu = ps_times[0], fu_times[0]
    hist = metrics.get("ml.batch[bench-main]", MLMetrics.BATCH_CHUNK_MS)
    per_stage = {
        "rows_per_sec": round(n / t_ps, 1),
        "spread": {
            "min_s": round(ps_times[0], 4),
            "median_s": round(ps_times[len(ps_times) // 2], 4),
            "max_s": round(ps_times[-1], 4),
            "repeats": len(ps_times),
        },
    }
    fused = {
        "rows_per_sec": round(n / t_fu, 1),
        "spread": {
            "min_s": round(fu_times[0], 4),
            "median_s": round(fu_times[len(fu_times) // 2], 4),
            "max_s": round(fu_times[-1], 4),
            "repeats": len(fu_times),
        },
        "chunk_p50_ms": round(hist.quantile(0.5), 3) if hist else None,
    }
    sweep = [
        fused_leg(chunk_rows, depth, f"ml.batch[bench-{chunk_rows}-{depth}]")
        for chunk_rows in (8_192, 32_768, 131_072)
        for depth in (1, 2)
    ]
    return {
        "name": "pipeline_batch_transform_6stage_d32",
        "rows": n,
        "dim": d,
        "stages": 6,
        "per_stage_rows_per_sec": per_stage["rows_per_sec"],
        "per_stage_spread": per_stage["spread"],
        "fused_rows_per_sec": fused["rows_per_sec"],
        "fused_spread": fused["spread"],
        "fused_chunk_p50_ms": fused["chunk_p50_ms"],
        "fused_vs_per_stage": round(
            fused["rows_per_sec"] / per_stage["rows_per_sec"], 2
        ),
        "sweep": sweep,
        "note": "per-stage = today's PipelineModel.transform loop (jit + "
        "readback + DataFrame per stage); fused = CompiledBatchPlan, one "
        "ingest/readback per chunk, columns device-resident across stages, "
        "double-buffered chunk prefetch. Bit-exactness of the two paths is "
        "pinned by tests/test_batch_fastpath.py.",
    }


def bench_sparse_pipelines():
    """Sparse/ragged fast path (docs/sparse.md): the two acceptance
    workloads, fused (sparse calling convention: ELL triples on the nnz-cap
    ladder, segment-reduce kernels, chains device-resident end to end) vs
    the per-stage fallback path, batch tier.

    - ``sparse_text_pipeline``: tokenize → hashingTF → IDF → logistic over
      ragged documents. Both legs pay the same host tokenize+hash featurize;
      the fused leg's win is everything downstream — no SparseVector
      materialization between stages, the counts/idf/margin chain as three
      AOT programs over the packed triple. An nnz-cap sweep sizes the
      ladder-padding cost.
    - ``sparse_ctr_pipeline``: one-hot → interaction → logistic (the CTR
      shape, nnz 1 per one-hot, cross dim = cats_a × cats_b never
      densified in the fused leg).

    Single-core hosts run with synchronous CPU dispatch like the other batch
    benches (restored on exit).
    """
    import os

    import jax

    if (os.cpu_count() or 1) == 1:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
        try:
            return _bench_sparse_pipelines_body()
        finally:
            jax.config.update("jax_cpu_enable_async_dispatch", True)
    return _bench_sparse_pipelines_body()


def _bench_sparse_pipelines_body():
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.builder.pipeline import Pipeline
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression
    from flink_ml_tpu.models.feature.hashing_tf import HashingTF
    from flink_ml_tpu.models.feature.idf import IDF
    from flink_ml_tpu.models.feature.interaction import Interaction
    from flink_ml_tpu.models.feature.one_hot_encoder import OneHotEncoder
    from flink_ml_tpu.models.feature.tokenizer import Tokenizer

    rng = np.random.default_rng(29)
    words = [f"w{i:03d}" for i in range(64)]

    def text_df(n, tokens_per_doc):
        docs = [
            " ".join(rng.choice(words, size=tokens_per_doc)) for _ in range(n)
        ]
        return DataFrame.from_dict(
            {"text": docs, "label": rng.integers(0, 2, n).astype(np.float64)}
        )

    def both_legs(model, df, repeats=3):
        n = len(df)
        config.set(Options.BATCH_FASTPATH, False)
        model.transform(df)  # warm per-stage jit caches
        t_slow, slow_spread = _median_time_spread(
            lambda: model.transform(df), repeats=repeats
        )
        config.set(Options.BATCH_FASTPATH, True)
        model.invalidate_batch_plan()
        model.transform(df)  # warm: compiles the chunk signatures
        t_fast, fast_spread = _median_time_spread(
            lambda: model.transform(df), repeats=repeats
        )
        config.unset(Options.BATCH_FASTPATH)
        return {
            "per_stage_rows_per_sec": round(n / t_slow, 1),
            "fused_rows_per_sec": round(n / t_fast, 1),
            "fused_vs_per_stage": round(t_slow / t_fast, 3),
            "per_stage_spread": slow_spread,
            "fused_spread": fast_spread,
        }

    # -- text ----------------------------------------------------------------
    n_text, dim = 50_000, 4096
    fit_df = text_df(2_000, 8)
    text_model = Pipeline(
        [
            Tokenizer().set_input_col("text").set_output_col("tokens"),
            HashingTF().set_input_col("tokens").set_output_col("tf").set_num_features(dim),
            IDF().set_input_col("tf").set_output_col("feat"),
            LogisticRegression()
            .set_features_col("feat")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_raw_prediction_col("raw")
            .set_max_iter(2),
        ]
    ).fit(fit_df)
    headline = both_legs(text_model, text_df(n_text, 8))
    cap_sweep = []
    for tokens in (4, 16, 64):
        config.set(Options.SPARSE_NNZ_CAP_MAX, 64)
        legs = both_legs(text_model, text_df(n_text // 5, tokens), repeats=3)
        config.unset(Options.SPARSE_NNZ_CAP_MAX)
        legs["tokens_per_doc"] = tokens
        from flink_ml_tpu.linalg.sparse_batch import ladder_cap

        legs["nnz_cap"] = ladder_cap(tokens)
        cap_sweep.append(legs)
    text = {
        "name": "sparse_text_pipeline",
        "chain": f"tokenize->hashingTF(d={dim})->idf->logistic, {n_text} docs x 8 tokens",
        **headline,
        "nnz_cap_sweep": cap_sweep,
        "note": (
            "both legs pay the same host tokenize+hash featurize; the fused "
            "leg chains counts/idf/margin on device over the packed ELL "
            "triple with zero SparseVector materialization between stages. "
            "1-core box: ratios are directional; the host featurize share "
            "shrinks (and the fused win grows) with vocabulary/doc size."
        ),
    }

    # -- CTR -----------------------------------------------------------------
    n_ctr, cats = 200_000, (1000, 500)
    fit = DataFrame.from_dict(
        {
            "ad": rng.integers(0, cats[0], 4_000).astype(np.float64),
            "user": rng.integers(0, cats[1], 4_000).astype(np.float64),
            "label": rng.integers(0, 2, 4_000).astype(np.float64),
        }
    )
    ctr_model = Pipeline(
        [
            OneHotEncoder()
            .set_input_cols("ad", "user")
            .set_output_cols("ad_v", "user_v")
            .set_handle_invalid("keep")
            .set_drop_last(False),
            Interaction().set_input_cols("ad_v", "user_v").set_output_col("cross"),
            LogisticRegression()
            .set_features_col("cross")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_raw_prediction_col("raw")
            .set_max_iter(2),
        ]
    ).fit(fit)
    ctr_df = DataFrame.from_dict(
        {
            "ad": rng.integers(0, cats[0], n_ctr).astype(np.float64),
            "user": rng.integers(0, cats[1], n_ctr).astype(np.float64),
        }
    )
    ctr = {
        "name": "sparse_ctr_pipeline",
        "chain": (
            f"one-hot({cats[0]},{cats[1]})->interaction(cross dim "
            f"{cats[0] * cats[1]})->logistic, {n_ctr} rows"
        ),
        **both_legs(ctr_model, ctr_df),
        "note": (
            "nnz 1 per one-hot; the fused leg never densifies the "
            f"{cats[0] * cats[1]}-dim cross — margins ride the "
            "gather-scale-segment-sum head at cap 1. 1-core box note as above."
        ),
    }
    out = {"name": "sparse_pipelines", "workloads": [text, ctr]}
    print(json.dumps(out, indent=1))
    return out


def bench_fusion_sweep():
    """Fusion tiers (docs/fusion.md): ``fusion.mode=exact`` vs ``fast`` vs
    ``fast`` with Pallas megakernels forced hot, on the two benched chains —
    the 6-stage feature chain (400k × 32, chunked batch transform) and the
    serving heads (scaler → logistic d=32 and scaler → MLP 256→512→512→8 at
    bucket 64, p50/p99 per batch).

    What each leg measures on this box: the exact tier compiles one program
    per reduction-bearing stage (3 programs for the 6-stage chain, 2 for each
    serving head); the fast tier merges each chain into ONE XLA program —
    the win here is per-program dispatch + XLA fusing elementwise math into
    the neighbouring reduction. The megakernel leg runs under
    ``pallas.interpret`` on CPU (the tier-1 fallback): it proves the code
    path and prices the interpreter, NOT the VMEM-residency win — on real
    TPUs the megakernel is where the BENCH_r05 flash-attention-style 4.7×
    lives. Ulp envelopes of every fast leg are pinned by
    tests/test_fusion.py.
    """
    import os

    import jax

    if (os.cpu_count() or 1) == 1:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
        try:
            return _bench_fusion_sweep_body()
        finally:
            jax.config.update("jax_cpu_enable_async_dispatch", True)
    return _bench_fusion_sweep_body()


def _bench_fusion_sweep_body():
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.builder.batch_plan import CompiledBatchPlan
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable.builder import PipelineModelServable
    from flink_ml_tpu.servable.fusion import FusionTier, ULP_ENVELOPE
    from flink_ml_tpu.servable.lib import (
        LogisticRegressionModelServable,
        MLPClassifierModelServable,
        StandardScalerModelServable,
    )
    from flink_ml_tpu.serving.plan import CompiledServingPlan

    rng = np.random.default_rng(9)
    n, d = 400_000, 32
    df = DataFrame.from_dict({"input": rng.standard_normal((n, d))})

    # Same rng draw order as the old inline construction — identical params.
    stages = _make_feature6_stages(rng, d, n_docs=n)

    tiers = {
        "exact": None,
        "fast": FusionTier("fast", megakernel=False),
        "megakernel": FusionTier("fast", megakernel=True, min_score=1.0),
    }

    # Batch chain: interleaved best-of-N (the pyperf min protocol of
    # pipeline_batch_transform — this box's ambient load swings 3x).
    plans = {
        name: CompiledBatchPlan.build(stages, scope=f"ml.batch[fusion-{name}]", fusion=tier)
        for name, tier in tiers.items()
    }
    for plan in plans.values():  # warm both chunk signatures, twice
        plan.transform(df)
        plan.transform(df)
    times = {name: [] for name in plans}
    for _ in range(7):
        for name, plan in plans.items():
            t0 = time.perf_counter()
            plan.transform(df)
            times[name].append(time.perf_counter() - t0)
    batch_rows = {}
    for name, ts in times.items():
        ts.sort()
        batch_rows[name] = {
            "rows_per_sec": round(n / ts[0], 1),
            "spread": {
                "min_s": round(ts[0], 4),
                "median_s": round(ts[len(ts) // 2], 4),
                "max_s": round(ts[-1], 4),
                "repeats": len(ts),
            },
            "programs_per_chunk": (
                len(plans[name].segments[0].programs)
            ),
            "megakernel_compiles": metrics.get(
                f"ml.batch[fusion-{name}]", MLMetrics.FUSION_PROGRAMS_MEGAKERNEL, 0
            ),
        }

    # Serving heads: closed-loop p50/p99 per 64-row batch through the
    # compiled plan (the micro-batcher's exec step, isolated).
    def serving_chain(servable, dim, reps=400):
        r = np.random.default_rng(1)
        batch = DataFrame.from_dict({"features": r.standard_normal((64, dim))})
        out = {}
        for name, tier in tiers.items():
            plan = CompiledServingPlan.build(
                servable, scope=f"ml.serving[fusion-{name}]", fusion=tier
            )
            plan.execute(batch)
            plan.execute(batch)
            lat = []
            for _ in range(reps):
                t0 = time.perf_counter()
                plan.execute(batch)
                lat.append((time.perf_counter() - t0) * 1e3)
            lat.sort()
            p50 = lat[len(lat) // 2]
            out[name] = {
                "latency_p50_ms": round(p50, 4),
                "latency_p99_ms": round(lat[int(len(lat) * 0.99)], 4),
                "rows_per_sec": round(64 / (p50 / 1e3), 1),
            }
        return out

    sc = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
    sc.set_with_mean(True)
    sc.mean = rng.standard_normal(d)
    sc.std = np.abs(rng.standard_normal(d)) + 0.5
    lr = LogisticRegressionModelServable().set_features_col("scaled")
    lr.coefficient = rng.standard_normal(d)
    lr_rows = serving_chain(PipelineModelServable([sc, lr]), d)

    sc2 = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
    sc2.set_with_mean(True)
    sc2.mean = rng.standard_normal(256)
    sc2.std = np.abs(rng.standard_normal(256)) + 0.5
    mlp = MLPClassifierModelServable().set_features_col("scaled")
    dims = [256, 512, 512, 8]
    arrays = {"labels": np.arange(8.0)}
    for i in range(3):
        arrays[f"W{i}"] = (
            rng.standard_normal((dims[i], dims[i + 1])) / np.sqrt(dims[i])
        ).astype(np.float32)
        arrays[f"b{i}"] = rng.standard_normal(dims[i + 1]).astype(np.float32)
    mlp._apply_model_arrays(arrays)
    mlp_rows = serving_chain(PipelineModelServable([sc2, mlp]), 256)

    return {
        "name": "fusion_sweep",
        "batch_6stage_400k_d32": batch_rows,
        "batch_fast_vs_exact": round(
            batch_rows["fast"]["rows_per_sec"] / batch_rows["exact"]["rows_per_sec"], 3
        ),
        "serving_scale_logistic_d32_b64": lr_rows,
        "serving_logistic_fast_vs_exact": round(
            lr_rows["fast"]["rows_per_sec"] / lr_rows["exact"]["rows_per_sec"], 3
        ),
        "serving_scale_mlp_256_512_512_8_b64": mlp_rows,
        "serving_mlp_fast_vs_exact": round(
            mlp_rows["fast"]["rows_per_sec"] / mlp_rows["exact"]["rows_per_sec"], 3
        ),
        "ulp_envelopes": dict(ULP_ENVELOPE),
        "note": "exact = per-stage programs (bit-identical to the per-stage "
        "path); fast = ONE cross-reduction XLA program per fusable chain "
        "(ulp-envelope numerics, tests/test_fusion.py); megakernel = the "
        "same chain as ONE Pallas kernel — on this CPU box it runs "
        "interpret-mode (code-path proof + interpreter price; the batch leg "
        "is expected SLOWER than fast), on TPU it is the VMEM-residency "
        "tier. The fast-vs-exact ratios are the honest CPU win: mostly "
        "saved per-program dispatch.",
    }


def bench_precision_sweep():
    """Precision tiers (docs/precision.md): ``precision.mode=f32`` vs
    ``bf16`` vs ``int8`` on the four benched chains — the serving heads
    (scaler → logistic d=32 and scaler → MLP 256→512→512→8 at bucket 64,
    p50/p99 per batch), the 6-stage feature chain (400k × 32, chunked batch
    transform), and the fused sparse CTR chain (one-hot → interaction →
    logistic, config-resolved tier through the Pipeline fast path).

    What each leg measures on this box: bf16 rounds activations to the bf16
    grid at ingest and every unfused stage boundary with f32 accumulation
    inside each program; int8 is the same transport over publish-time
    dequantized int8 weights (the serving path never quantizes — the int8
    serving legs here run weights through ``quantize_array_int8`` /
    ``quantize_model_arrays`` exactly as ``publish_servable(...,
    precision="int8")`` would). Ulp envelopes of every lowp leg are pinned
    by tests/test_precision.py.
    """
    import os

    import jax

    if (os.cpu_count() or 1) == 1:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
        try:
            return _bench_precision_sweep_body()
        finally:
            jax.config.update("jax_cpu_enable_async_dispatch", True)
    return _bench_precision_sweep_body()


def _bench_precision_sweep_body():
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.builder.batch_plan import CompiledBatchPlan
    from flink_ml_tpu.builder.pipeline import Pipeline
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression
    from flink_ml_tpu.models.feature.interaction import Interaction
    from flink_ml_tpu.models.feature.one_hot_encoder import OneHotEncoder
    from flink_ml_tpu.servable.builder import PipelineModelServable
    from flink_ml_tpu.servable.lib import (
        LogisticRegressionModelServable,
        MLPClassifierModelServable,
        StandardScalerModelServable,
    )
    from flink_ml_tpu.servable.precision import (
        PRECISION_TIER_DEVIATION,
        PrecisionTier,
        quantize_array_int8,
        quantize_model_arrays,
    )
    from flink_ml_tpu.serving.plan import CompiledServingPlan

    rng = np.random.default_rng(31)
    n, d = 400_000, 32
    tiers = {
        "f32": PrecisionTier("f32"),
        "bf16": PrecisionTier("bf16"),
        "int8": PrecisionTier("int8"),
    }

    # Serving heads: closed-loop p50/p99 per 64-row batch through the
    # compiled plan (the micro-batcher's exec step, isolated). One servable
    # per tier because the int8 leg serves different (publish-quantized)
    # weights — same params across the f32/bf16 pair.
    def serving_chain(servables, dim, reps=400):
        r = np.random.default_rng(1)
        batch = DataFrame.from_dict({"features": r.standard_normal((64, dim))})
        out = {}
        for name, tier in tiers.items():
            plan = CompiledServingPlan.build(
                servables[name], scope=f"ml.serving[precision-{name}]", precision=tier
            )
            plan.execute(batch)
            plan.execute(batch)
            lat = []
            for _ in range(reps):
                t0 = time.perf_counter()
                plan.execute(batch)
                lat.append((time.perf_counter() - t0) * 1e3)
            lat.sort()
            p50 = lat[len(lat) // 2]
            out[name] = {
                "latency_p50_ms": round(p50, 4),
                "latency_p99_ms": round(lat[int(len(lat) * 0.99)], 4),
                "rows_per_sec": round(64 / (p50 / 1e3), 1),
            }
        return out

    mean = rng.standard_normal(d)
    std = np.abs(rng.standard_normal(d)) + 0.5
    coef = rng.standard_normal(d)
    coef_q, _ = quantize_array_int8(coef)

    def scale_logistic(coefficient):
        sc = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
        sc.set_with_mean(True)
        sc.mean = mean
        sc.std = std
        lr = LogisticRegressionModelServable().set_features_col("scaled")
        lr.coefficient = coefficient
        return PipelineModelServable([sc, lr])

    lr_rows = serving_chain(
        {"f32": scale_logistic(coef), "bf16": scale_logistic(coef), "int8": scale_logistic(coef_q)},
        d,
    )

    mean2 = rng.standard_normal(256)
    std2 = np.abs(rng.standard_normal(256)) + 0.5
    dims = [256, 512, 512, 8]
    arrays = {"labels": np.arange(8.0)}
    for i in range(3):
        arrays[f"W{i}"] = (
            rng.standard_normal((dims[i], dims[i + 1])) / np.sqrt(dims[i])
        ).astype(np.float32)
        arrays[f"b{i}"] = rng.standard_normal(dims[i + 1]).astype(np.float32)
    arrays_q, _ = quantize_model_arrays(arrays)

    def scale_mlp(model_arrays):
        sc = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
        sc.set_with_mean(True)
        sc.mean = mean2
        sc.std = std2
        mlp = MLPClassifierModelServable().set_features_col("scaled")
        mlp._apply_model_arrays(model_arrays)
        return PipelineModelServable([sc, mlp])

    mlp_rows = serving_chain(
        {"f32": scale_mlp(arrays), "bf16": scale_mlp(arrays), "int8": scale_mlp(arrays_q)},
        256,
    )

    # Batch chain: interleaved best-of-N over the 6-stage feature chain (the
    # pyperf min protocol — this box's ambient load swings 3x). The chain
    # has no int8-eligible weights, so the int8 leg prices the same bf16
    # transport (the ≡-bf16 row in PRECISION_TIER_DEVIATION).
    df = DataFrame.from_dict({"input": rng.standard_normal((n, d))})
    stages = _make_feature6_stages(rng, d, n_docs=n)
    plans = {
        name: CompiledBatchPlan.build(
            stages, scope=f"ml.batch[precision-{name}]", precision=tier
        )
        for name, tier in tiers.items()
    }
    for plan in plans.values():  # warm both chunk signatures, twice
        plan.transform(df)
        plan.transform(df)
    times = {name: [] for name in plans}
    for _ in range(7):
        for name, plan in plans.items():
            t0 = time.perf_counter()
            plan.transform(df)
            times[name].append(time.perf_counter() - t0)
    batch_rows = {}
    for name, ts in times.items():
        ts.sort()
        batch_rows[name] = {
            "rows_per_sec": round(n / ts[0], 1),
            "spread": {
                "min_s": round(ts[0], 4),
                "median_s": round(ts[len(ts) // 2], 4),
                "max_s": round(ts[-1], 4),
                "repeats": len(ts),
            },
        }

    # Sparse CTR chain through the Pipeline fused path, tier resolved from
    # precision.mode config — the deployment route (docs/precision.md:
    # weights quantize at publish only, so this leg's int8 measures the
    # bf16 transport over the packed ELL triple).
    n_ctr, cats = 200_000, (1000, 500)
    fit = DataFrame.from_dict(
        {
            "ad": rng.integers(0, cats[0], 4_000).astype(np.float64),
            "user": rng.integers(0, cats[1], 4_000).astype(np.float64),
            "label": rng.integers(0, 2, 4_000).astype(np.float64),
        }
    )
    ctr_model = Pipeline(
        [
            OneHotEncoder()
            .set_input_cols("ad", "user")
            .set_output_cols("ad_v", "user_v")
            .set_handle_invalid("keep")
            .set_drop_last(False),
            Interaction().set_input_cols("ad_v", "user_v").set_output_col("cross"),
            LogisticRegression()
            .set_features_col("cross")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_raw_prediction_col("raw")
            .set_max_iter(2),
        ]
    ).fit(fit)
    ctr_df = DataFrame.from_dict(
        {
            "ad": rng.integers(0, cats[0], n_ctr).astype(np.float64),
            "user": rng.integers(0, cats[1], n_ctr).astype(np.float64),
        }
    )
    ctr_rows = {}
    config.set(Options.BATCH_FASTPATH, True)
    try:
        for name in tiers:
            if name == "f32":
                config.unset(Options.PRECISION_MODE)
            else:
                config.set(Options.PRECISION_MODE, name)
            ctr_model.invalidate_batch_plan()
            ctr_model.transform(ctr_df)  # warm: compiles the chunk signatures
            t, spread = _median_time_spread(
                lambda: ctr_model.transform(ctr_df), repeats=3
            )
            ctr_rows[name] = {
                "fused_rows_per_sec": round(n_ctr / t, 1),
                "spread": spread,
            }
    finally:
        config.unset(Options.PRECISION_MODE)
        config.unset(Options.BATCH_FASTPATH)

    return {
        "name": "precision_sweep",
        "serving_scale_logistic_d32_b64": lr_rows,
        "serving_logistic_bf16_vs_f32": round(
            lr_rows["bf16"]["rows_per_sec"] / lr_rows["f32"]["rows_per_sec"], 3
        ),
        "serving_logistic_int8_vs_f32": round(
            lr_rows["int8"]["rows_per_sec"] / lr_rows["f32"]["rows_per_sec"], 3
        ),
        "serving_scale_mlp_256_512_512_8_b64": mlp_rows,
        "serving_mlp_bf16_vs_f32": round(
            mlp_rows["bf16"]["rows_per_sec"] / mlp_rows["f32"]["rows_per_sec"], 3
        ),
        "serving_mlp_int8_vs_f32": round(
            mlp_rows["int8"]["rows_per_sec"] / mlp_rows["f32"]["rows_per_sec"], 3
        ),
        "batch_6stage_400k_d32": batch_rows,
        "batch_bf16_vs_f32": round(
            batch_rows["bf16"]["rows_per_sec"] / batch_rows["f32"]["rows_per_sec"], 3
        ),
        "sparse_ctr_fused_200k": ctr_rows,
        "sparse_ctr_bf16_vs_f32": round(
            ctr_rows["bf16"]["fused_rows_per_sec"]
            / ctr_rows["f32"]["fused_rows_per_sec"],
            3,
        ),
        "tier_deviation_envelopes_ulps": {
            f"{chain}/{mode}": ulps
            for (chain, mode), ulps in sorted(PRECISION_TIER_DEVIATION.items())
        },
        "note": "HONEST 1-CORE NOTE: on XLA CPU there is no bf16 ALU and no "
        "bandwidth-bound transport, so the bf16/int8 legs PAY for the "
        "rounding casts at every stage boundary and win nothing back — "
        "expect parity-to-slower vs f32 here. The tier is an accelerator "
        "play: activations cross fused-segment boundaries at half width and "
        "the published int8 artifact halves the weight payload again (the "
        "cost model prices exactly those bytes). These rows pin the code "
        "path and price the cast overhead honestly; the numerics envelopes "
        "are the contract (tests/test_precision.py), and int8 quantization "
        "happens at publish only — in-flight legs never quantize.",
    }


_SHARDED_NOTE = (
    "HONEST NOTE: measured on a 1-core dev box with "
    "--xla_force_host_platform_device_count=8 — the 8 'devices' time-share "
    "one core, so these rows measure SPMD DISPATCH OVERHEAD (partitioning, "
    "per-shard buffers, collective plumbing), not speedup. On real chips the "
    "same programs split N-ways in wall time; here mesh>1 legs are expected "
    "to run SLOWER than mesh=1. Bit-exactness vs mesh=1 is pinned by "
    "tests/test_sharded_plans.py."
)


def _bench_serving_sharded_body():
    """Mesh sweep over the sharded serving fast path (child process only —
    requires the forced 8-device grid; see bench_sharded_fanout)."""
    import threading

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable import PipelineModelServable
    from flink_ml_tpu.servable.lib import (
        LogisticRegressionModelServable,
        StandardScalerModelServable,
    )
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    rng = np.random.default_rng(5)
    dim = 256
    X = rng.standard_normal((4096, dim)).astype(np.float32)

    def make_pipeline():
        scaler = (
            StandardScalerModelServable()
            .set_input_col("features")
            .set_output_col("scaled")
            .set_with_mean(True)
        )
        scaler.mean = rng.standard_normal(dim).astype(np.float32)
        scaler.std = (np.abs(rng.standard_normal(dim)) + 0.5).astype(np.float32)
        lr = LogisticRegressionModelServable().set_features_col("scaled")
        lr.coefficient = rng.standard_normal(dim).astype(np.float32)
        return PipelineModelServable([scaler, lr])

    n_threads, requests_per_thread, req_rows = 2, 60, 8
    sweep = []
    for mesh in (1, 2, 4, 8):
        server = InferenceServer(
            make_pipeline(),
            name=f"bench-shard-{mesh}",
            serving_config=ServingConfig(
                max_batch_size=64,
                max_delay_ms=1.0,
                queue_capacity_rows=8192,
                default_timeout_ms=120_000,
                mesh=mesh,
            ),
            warmup_template=DataFrame.from_dict({"features": X[:1]}),
        )
        try:
            barrier = threading.Barrier(n_threads + 1)

            def client(tid):
                barrier.wait()
                for i in range(requests_per_thread):
                    j = (tid * 997 + i * 61) % (X.shape[0] - req_rows)
                    server.predict(
                        DataFrame.from_dict({"features": X[j : j + req_rows]})
                    )

            threads = [
                threading.Thread(target=client, args=(t,)) for t in range(n_threads)
            ]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            scraped = metrics.scope(server.scope)
            lat = scraped[MLMetrics.SERVING_LATENCY_MS]
            total_rows = n_threads * requests_per_thread * req_rows
            sweep.append(
                {
                    "mesh": mesh,
                    "buckets": list(server._batcher.buckets),
                    "rows_per_sec": round(total_rows / elapsed, 1),
                    "latency_p50_ms": round(lat.quantile(0.5), 3),
                    "latency_p99_ms": round(lat.quantile(0.99), 3),
                    "fastpath_compiles": scraped.get(
                        MLMetrics.SERVING_FASTPATH_COMPILES, 0
                    ),
                    "shard_rows": scraped.get(MLMetrics.SERVING_SHARD_ROWS, 0),
                    "warmup_compile_ms": round(
                        scraped.get(MLMetrics.SERVING_WARMUP_COMPILE_MS, 0.0), 1
                    ),
                }
            )
        finally:
            server.close()
    return {
        "name": "serving_sharded_scaler_lr_d256",
        "threads": n_threads,
        "requests_per_thread": requests_per_thread,
        "request_rows": req_rows,
        "sweep": sweep,
        "note": _SHARDED_NOTE,
    }


def _bench_batch_sharded_body():
    """Mesh sweep over the sharded batch-transform fast path (child process
    only — see bench_sharded_fanout)."""
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.builder.batch_plan import CompiledBatchPlan
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable.lib import (
        LogisticRegressionModelServable,
        StandardScalerModelServable,
    )
    from flink_ml_tpu.servable.sharding import resolve_plan_sharding

    rng = np.random.default_rng(9)
    n, d = 200_000, 32
    df = DataFrame.from_dict({"features": rng.standard_normal((n, d))})
    scaler = (
        StandardScalerModelServable()
        .set_input_col("features")
        .set_output_col("scaled")
        .set_with_mean(True)
    )
    scaler.mean = rng.standard_normal(d)
    scaler.std = np.abs(rng.standard_normal(d)) + 0.5
    lr = LogisticRegressionModelServable().set_features_col("scaled")
    lr.coefficient = rng.standard_normal(d)
    stages = [scaler, lr]

    config.set(Options.BATCH_CHUNK_ROWS, 32_768)
    sweep = []
    try:
        for mesh in (1, 2, 4, 8):
            scope = f"ml.batch[bench-shard-{mesh}]"
            sharding = resolve_plan_sharding(mesh)
            plan = CompiledBatchPlan.build(stages, scope=scope, sharding=sharding)
            plan.transform(df)  # warm: compiles the chunk signatures
            t, spread = _median_time_spread(lambda: plan.transform(df), repeats=3)
            sweep.append(
                {
                    "mesh": mesh,
                    "rows_per_sec": round(n / t, 1),
                    "spread": spread,
                    "shard_rows": metrics.get(scope, MLMetrics.BATCH_SHARD_ROWS, 0),
                    "shard_pad_rows": metrics.get(
                        scope, MLMetrics.BATCH_SHARD_PAD_ROWS, 0
                    ),
                    "replicated_chunks": metrics.get(
                        scope, MLMetrics.BATCH_SHARD_REPLICATED_CHUNKS, 0
                    ),
                }
            )
    finally:
        config.unset(Options.BATCH_CHUNK_ROWS)
    return {
        "name": "batch_sharded_scaler_lr_200k_d32",
        "rows": n,
        "dim": d,
        "chunk_rows": 32_768,
        "sweep": sweep,
        "note": _SHARDED_NOTE,
    }


def _bench_sharded_trace_attrs():
    """One traced mesh=4 burst: the per-shard span attrs BENCH rounds record
    so traceview's shard section is reproducible from the artifact."""
    from flink_ml_tpu import trace
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.servable.lib import LogisticRegressionModelServable
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    rng = np.random.default_rng(3)
    dim = 64
    servable = LogisticRegressionModelServable().set_features_col("features")
    servable.coefficient = rng.standard_normal(dim).astype(np.float32)
    X = rng.standard_normal((256, dim)).astype(np.float32)
    with trace.capture() as recorder:
        with InferenceServer(
            servable,
            name="bench-shard-trace",
            serving_config=ServingConfig(
                max_batch_size=64, max_delay_ms=0.0, default_timeout_ms=60_000,
                mesh=4,
            ),
            warmup_template=DataFrame.from_dict({"features": X[:1]}),
        ) as server:
            for i in range(16):
                j = (i * 31) % (X.shape[0] - 4)
                server.predict(DataFrame.from_dict({"features": X[j : j + 4]}))
    spans = recorder.snapshot()
    sharded = [
        s for s in spans
        if s.attrs and s.attrs.get("shards") == 4
        and s.name in ("serving.dispatch", "serving.exec", "serving.batch")
    ]
    by_name = {}
    for s in sharded:
        entry = by_name.setdefault(
            s.name, {"count": 0, "total_ms": 0.0, "shards": 4, "shard_rows": None}
        )
        entry["count"] += 1
        entry["total_ms"] = round(entry["total_ms"] + s.duration * 1000.0, 3)
        if isinstance(s.attrs.get("shard_rows"), int):
            entry["shard_rows"] = s.attrs["shard_rows"]
    return {
        "mesh": 4,
        "sharded_spans": len(sharded),
        "per_span": by_name,
        "note": "spans carrying shards/shard_rows attrs; traceview divides "
        "their device time per shard (tools/traceview.py shard section)",
    }


def _sharded_child() -> None:
    """Entry point of the forced-8-device child (bench_sharded_fanout)."""
    print(
        json.dumps(
            {
                "serving_sharded": _bench_serving_sharded_body(),
                "batch_sharded": _bench_batch_sharded_body(),
                "trace_shard_attrs": _bench_sharded_trace_attrs(),
            }
        )
    )


def bench_sharded_fanout():
    """Pod-scale fan-out sweep (serving.mesh / batch.mesh 1-8) in a
    tunnel-free subprocess on the 8-device virtual CPU grid — the same
    re-exec pattern as bench_streamed_overlap_cpu_mesh, because the sharded
    paths need the forced device count before jax initializes."""
    import os
    import subprocess

    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": (
                env.get("XLA_FLAGS", "")
                + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=30"
                + " --xla_cpu_collective_call_terminate_timeout_seconds=120"
                + " --xla_force_host_platform_device_count=8"
            ).strip(),
            "PYTHONPATH": os.path.dirname(os.path.abspath(__file__)),
        }
    )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--sharded-child"],
            capture_output=True, text=True, timeout=1800, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        payload = json.loads(proc.stdout.strip().splitlines()[-1])
        payload["name"] = "sharded_fanout_mesh_sweep"
        return payload
    except Exception as e:  # never sink the whole bench for the side artifact
        return {"name": "sharded_fanout_mesh_sweep", "error": f"{type(e).__name__}: {e}"}


def bench_tracing_overhead():
    """graftscope acceptance row (docs/observability.md): the same
    single-client serving loop with tracing off vs on.

    Off is the default production state — the contract is that the disabled
    tracer is one attribute check per instrumented site, so the off leg must
    match the untraced PR 7 baseline path (tier-1 asserts the structural
    half: zero spans, shared no-op span, no per-request span allocation; this
    row quantifies the residual). The on leg prices full span recording —
    ~7 spans per request — for capacity planning.
    """
    from flink_ml_tpu import trace
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable.lib import LogisticRegressionModelServable
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    rng = np.random.default_rng(17)
    dim = 256
    X = rng.standard_normal((2048, dim)).astype(np.float32)
    requests = 400
    req_rows = 8

    def run_leg(name):
        servable = LogisticRegressionModelServable().set_features_col("features")
        servable.coefficient = rng.standard_normal(dim).astype(np.float32)
        server = InferenceServer(
            servable,
            name=name,
            serving_config=ServingConfig(
                max_batch_size=64,
                max_delay_ms=0.0,  # single client: coalescing buys nothing
                default_timeout_ms=120_000,
            ),
            warmup_template=DataFrame.from_dict({"features": X[:1]}),
        )
        try:
            t0 = time.perf_counter()
            for i in range(requests):
                j = (i * 61) % (X.shape[0] - req_rows)
                server.predict(DataFrame.from_dict({"features": X[j : j + req_rows]}))
            elapsed = time.perf_counter() - t0
            hist = metrics.histogram(server.scope, MLMetrics.SERVING_LATENCY_MS)
            p50, p99 = hist.quantiles((0.5, 0.99))
            return {
                "requests": requests,
                "request_rows": req_rows,
                "rows_per_sec": round(requests * req_rows / elapsed, 1),
                "latency_p50_ms": round(p50, 3),
                "latency_p99_ms": round(p99, 3),
            }
        finally:
            server.close()

    off = run_leg("bench-trace-off")
    assert not trace.tracer.enabled
    with trace.capture() as recorder:
        on = run_leg("bench-trace-on")
        on["spans"] = recorder.recorded
        report = recorder.goodput_report()
        on["goodput_fraction"] = round(
            report.fraction("ml.serving[bench-trace-on]") or 0.0, 4
        )
    overhead = (
        round(100.0 * (on["latency_p50_ms"] / off["latency_p50_ms"] - 1.0), 1)
        if off["latency_p50_ms"]
        else None
    )
    return {
        "name": "tracing_overhead_serving_microbatch",
        "off": off,
        "on": on,
        "p50_overhead_pct": overhead,
        "note": "single-client closed loop, d=256 logistic servable; off = "
        "default disabled tracer (one attribute check per site), on = full "
        "span recording incl. queue/pad/dispatch/readback tree per request",
    }


def bench_journal_overhead():
    """Flight-recorder acceptance row (docs/observability.md): the d=256
    logistic fast path with the always-on journal disabled vs enabled (the
    shipped default), as a paired median-of-ratios measurement.

    Protocol: 15 alternating off/on leg pairs (400 closed-loop requests
    each), per-leg p50 + mean latency, and the reported overhead is the
    MEDIAN of the 15 pairwise on/off ratios. One leg on this 1-core box
    carries heavy-tailed scheduler noise (individual legs swing >15% in
    both directions — the per-pair ratios are recorded); pairing adjacent
    legs cancels slow drift and the median rejects the outlier legs, which
    best-of-N and single-pair protocols measurably do not here.

    The journal records *decisions*, not requests — the steady fast path
    reaches zero emit() sites, so the expected delta is zero by
    construction; this row prices the residual (the writer thread existing,
    the disabled-vs-armed branch) and the separate overload leg prices the
    emit sites that DO fire under load (sheds/deadline misses at 2x
    saturation: one bounded-queue enqueue each, writes on the
    flight-recorder thread — tests/test_telemetry.py asserts the thread
    discipline and the dispatch path's zero-write contract).
    """
    import statistics
    import tempfile

    import flink_ml_tpu.telemetry as telemetry
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.loadgen import OpenLoopLoadGenerator, ZipfSizes, ramp_schedule
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable.lib import LogisticRegressionModelServable
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    rng = np.random.default_rng(29)
    dim = 256
    X = rng.standard_normal((4096, dim)).astype(np.float32)
    requests = 400
    req_rows = 8

    def make_server(name, queue_capacity=1024):
        servable = LogisticRegressionModelServable().set_features_col("features")
        servable.coefficient = rng.standard_normal(dim).astype(np.float32)
        return InferenceServer(
            servable,
            name=name,
            serving_config=ServingConfig(
                max_batch_size=64,
                max_delay_ms=0.0,  # single client: coalescing buys nothing
                queue_capacity_rows=queue_capacity,
                default_timeout_ms=30_000,
                shed_sustain_ms=10.0,
            ),
            warmup_template=DataFrame.from_dict({"features": X[:1]}),
        )

    def leg(name):
        """(p50 ms, mean ms/request) of one closed-loop leg."""
        server = make_server(name)
        try:
            t0 = time.perf_counter()
            for i in range(requests):
                j = (i * 61) % (X.shape[0] - req_rows)
                server.predict(DataFrame.from_dict({"features": X[j : j + req_rows]}))
            mean_ms = (time.perf_counter() - t0) / requests * 1000.0
            hist = metrics.histogram(server.scope, MLMetrics.SERVING_LATENCY_MS)
            return hist.quantile(0.5), mean_ms
        finally:
            server.close()

    pairs = 15
    off_p50s, on_p50s, p50_ratios, mean_ratios = [], [], [], []
    try:
        telemetry.configure(enabled=False)
        leg("bench-journal-warm")  # discarded: pays the process-wide compiles
        for r in range(pairs):
            order = ("off", "on") if r % 2 == 0 else ("on", "off")
            results = {}
            for mode in order:
                if mode == "off":
                    telemetry.configure(enabled=False)
                else:
                    telemetry.configure(tempfile.mkdtemp(prefix="bench-journal-"))
                results[mode] = leg(f"bench-journal-{mode}-{r}")
            off_p50s.append(results["off"][0])
            on_p50s.append(results["on"][0])
            p50_ratios.append(results["on"][0] / results["off"][0])
            mean_ratios.append(results["on"][1] / results["off"][1])
        # Overload leg (journal on): ~2x a measured saturation, where the
        # shed/deadline decision sites actually emit.
        recorder = telemetry.configure(tempfile.mkdtemp(prefix="bench-journal-"))
        sizes = ZipfSizes((1, 2, 4, 8, 16, 32), alpha=1.5)
        server = make_server("bench-journal-overload", queue_capacity=256)

        def request(rows):
            j = int(rng.integers(0, X.shape[0] - rows))
            return DataFrame.from_dict({"features": X[j : j + rows]})

        overload_rps = 8000.0  # ~2x this head's measured ~4k rps saturation
        try:
            sched = ramp_schedule(
                [(overload_rps, 1.0)], sizes=sizes, priority_mix={0: 0.7, 1: 0.3}, seed=9
            )
            report = OpenLoopLoadGenerator(
                sched, request, timeout_ms={0: 30_000.0, 1: 250.0}
            ).run(server)
            step = report.steps[0]
        finally:
            server.close()
        recorder.flush(10.0)
        overload = {
            "offered_rps": overload_rps,
            "latency_p50_ms": round(step.latency_ms(0.5), 3),
            "shed": step.shed,
            "deadline_misses": step.deadline_misses,
            "journal_events": recorder.seq,
            "journal_dropped": recorder.dropped,
        }
    finally:
        telemetry.configure(None)
    p50_med = statistics.median(p50_ratios)
    mean_med = statistics.median(mean_ratios)
    return {
        "name": "journal_overhead_serving_microbatch",
        "pairs": pairs,
        "requests_per_leg": requests,
        "request_rows": req_rows,
        "off": {"median_latency_p50_ms": round(statistics.median(off_p50s), 3)},
        "on": {"median_latency_p50_ms": round(statistics.median(on_p50s), 3)},
        "p50_pairwise_ratios": [round(x, 3) for x in p50_ratios],
        "p50_overhead_pct": round(100.0 * (p50_med - 1.0), 2),
        "mean_latency_overhead_pct": round(100.0 * (mean_med - 1.0), 2),
        "overload_on": overload,
        "note": "d=256 logistic fast path, single-client closed loop; off = "
        "observability.journal disabled, on = the shipped always-on "
        "default. Overhead = median of 15 pairwise on/off ratios (paired "
        "legs cancel drift, the median rejects this box's heavy-tailed "
        "scheduler outliers — individual legs swing >15% both directions, "
        "see the recorded ratios). The steady path reaches zero emit() "
        "sites by design; overload_on exercises the shed/deadline emit "
        "sites (one bounded-queue enqueue each, journal_dropped must stay "
        "0, writes only on the flight-recorder thread per "
        "tests/test_telemetry.py).",
    }


def bench_mlp_forward(peak_flops):
    import jax
    import jax.numpy as jnp

    import __graft_entry__

    fn, (params, X) = __graft_entry__.entry()
    params = [(jnp.asarray(W, jnp.bfloat16), jnp.asarray(b, jnp.bfloat16)) for W, b in params]
    X = jnp.asarray(X, jnp.bfloat16)
    step = jax.jit(fn)

    jax.block_until_ready(step(params, X))
    reps = 100
    t0 = time.perf_counter()
    outs = [step(params, X) for _ in range(reps)]  # pipelined async dispatch
    np.asarray(outs[-1][0])  # forces the whole dependency chain to finish
    elapsed = (time.perf_counter() - t0) / reps
    batch = X.shape[0]
    flops = 2.0 * batch * sum(int(W.shape[0]) * int(W.shape[1]) for W, _ in params)
    achieved = flops / elapsed
    return {
        "name": "mlp_forward_bf16_b4096_256_512_512_8",
        "rows_per_sec": round(batch / elapsed, 1),
        "step_time_us": round(elapsed * 1e6, 1),
        "achieved_gflops": round(achieved / 1e9, 1),
        "mfu": round(achieved / peak_flops, 4) if peak_flops else None,
        "latency_target_us": 5000,
        "note": "serving shape: bandwidth-bound by design (weights re-read per "
        "call), so low MFU is expected — the quantified contract is the "
        "latency target, met with ~4x headroom; for throughput, batch up "
        "(mlp_train shows the same network at 78% MFU at batch 32k)",
        "latency_target_source": "half the ~10 ms model-inference slice of "
        "the classic 100 ms real-time-bidding budget (the Criteo CTR "
        "setting BASELINE.json's north star lives in): scoring must leave "
        "room for feature transforms in the same window, the role the "
        "reference's servable path plays downstream of its online models",
    }


def bench_retrieval_topk():
    """Retrieval tier (docs/retrieval.md): top-K serving latency at catalog
    scale under OPEN-LOOP load — the p99 a capacity plan is made of, at the
    candidate counts the recsys family actually carries (10^5 and 10^6).

    Per (candidates, K) cell: a swing ``CandidateIndex`` is synthesized at
    scale (ELL neighbor table, 16 slots/row), served through
    ``InferenceServer`` with the sparse nnz ladder x K rung warmed up front,
    then driven with seeded Poisson single-row arrivals (every request: an
    8-item history + its own ``k``) at ~0.6x of a measured saturation burst.
    Recorded: achieved qps, p50/p99 latency, zero post-warmup compiles.
    1-core CPU box: absolute numbers are directional (XLA-CPU top_k over
    [batch, C]); the contract under test is the SHAPE of the path — fused,
    compile-free, p99 bounded while C grows 10x.
    """
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.linalg.vectors import SparseVector
    from flink_ml_tpu.loadgen import FixedSizes, OpenLoopLoadGenerator, ramp_schedule
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.retrieval import CandidateIndex

    NNZ = 8  # history items per request — one warmed nnz cap
    NBRS = 16  # ELL similarity slots per candidate row

    def make_index(C, seed):
        rng = np.random.default_rng(seed)
        sim_ids = rng.integers(0, C, (C, NBRS)).astype(np.int32)
        sim_ids.sort(axis=1)  # the sorted-per-row scatter invariant
        sim_values = rng.random((C, NBRS), np.float32) + np.float32(0.01)
        idx = CandidateIndex(
            {
                "item_ids": np.arange(C, dtype=np.int64),
                "sim_values": sim_values,
                "sim_ids": sim_ids,
            }
        )
        idx.set_output_col("rec")
        return idx

    rows = []
    for C in (100_000, 1_000_000):
        idx = make_index(C, seed=C)
        rng = np.random.default_rng(17)
        # pre-drawn request pool: arrival threads must not pay rng/pack cost
        pool = [
            DataFrame(
                ["history", "k"],
                None,
                [
                    [
                        SparseVector(
                            C,
                            np.sort(
                                rng.choice(C, size=NNZ, replace=False)
                            ).astype(np.int64),
                            np.ones(NNZ),
                        )
                    ],
                    np.asarray([0], np.int64),  # k patched per cell below
                ],
            )
            for _ in range(64)
        ]
        for K in (10, 100):
            from flink_ml_tpu.serving import InferenceServer, ServingConfig

            config.set(Options.SPARSE_WARMUP_CAPS, str(NNZ))
            config.set(Options.SPARSE_NNZ_CAP_MAX, NNZ)
            config.set(Options.RETRIEVAL_WARMUP_KS, str(K))
            config.set(Options.RETRIEVAL_K_CAP_MAX, 128)
            reqs = [
                DataFrame(
                    df.column_names, None, [df.column("history"), np.asarray([K], np.int64)]
                )
                for df in pool
            ]
            req_i = [0]

            def request(_rows):
                req_i[0] = (req_i[0] + 1) % len(reqs)
                return reqs[req_i[0]]

            name = f"bench-ret-{C}-{K}"
            scope = f"ml.serving[{name}]"
            template = reqs[0]
            server = InferenceServer(
                idx.servable(),
                name=name,
                serving_config=ServingConfig(
                    max_batch_size=8,
                    max_delay_ms=1.0,
                    queue_capacity_rows=256,
                    default_timeout_ms=60_000,
                ),
                warmup_template=template,
            )
            try:
                compiles0 = metrics.get(
                    scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0
                )
                # saturation estimate: a short deliberately-overloaded burst
                cal = OpenLoopLoadGenerator(
                    ramp_schedule([(400.0, 1.0)], sizes=FixedSizes(1), seed=1),
                    request,
                    timeout_ms=60_000.0,
                ).run(server)
                sat_qps = max(cal.total_resolved / cal.wall_s, 1.0)
                rate = 0.6 * sat_qps
                report = OpenLoopLoadGenerator(
                    ramp_schedule([(rate, 4.0)], sizes=FixedSizes(1), seed=2),
                    request,
                    timeout_ms=60_000.0,
                ).run(server)
                step = report.steps[0]
                compiles = (
                    metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)
                    - compiles0
                )
                rows.append(
                    {
                        "candidates": C,
                        "k": K,
                        "k_rung": 16 if K == 10 else 128,
                        "saturation_qps": round(sat_qps, 1),
                        "offered_qps": round(rate, 1),
                        "achieved_qps": round(
                            step.completed / max(step.duration_s, 1e-9), 1
                        ),
                        "p50_ms": round(step.latency_ms(0.5) or 0.0, 2),
                        "p99_ms": round(step.latency_ms(0.99) or 0.0, 2),
                        "fully_resolved": report.fully_resolved(),
                        "post_warmup_compiles": compiles,
                    }
                )
            finally:
                server.close()
                for opt in (
                    Options.SPARSE_WARMUP_CAPS,
                    Options.SPARSE_NNZ_CAP_MAX,
                    Options.RETRIEVAL_WARMUP_KS,
                    Options.RETRIEVAL_K_CAP_MAX,
                ):
                    config.unset(opt)
    return {
        "name": "retrieval_topk_open_loop",
        "chain": "8-item history -> fused segment-reduce swing scores -> "
        "lax.top_k, served single-row open-loop @ 0.6x saturation",
        "sweep": rows,
        "note": "device-resident swing index (16 ELL slots/row); every cell "
        "fused with zero post-warmup compiles. 1-core XLA-CPU box: "
        "absolute qps/latency directional only — the recorded contract "
        "is p99 boundedness as C grows 10x and K 10x on the rung "
        "ladder, and the compile-free fast path holding under "
        "open-loop arrivals.",
    }


def main() -> None:
    import jax

    kind = jax.devices()[0].device_kind
    peak = _PEAK_FLOPS.get(kind)
    peak_bw = _PEAK_HBM_GBPS.get(kind)

    logreg, (X, y) = bench_logreg(peak, peak_bw)
    cpu_rows, cpu_spread = bench_logreg_cpu_baseline(X, y)
    logreg["cpu_baseline_rows_per_sec"] = round(cpu_rows, 1)
    logreg["cpu_baseline_spread"] = cpu_spread
    logreg["vs_cpu_baseline"] = round(logreg["steady_rows_per_sec"] / cpu_rows, 2)
    del X, y
    sparse = bench_logreg_sparse(peak, peak_bw)
    sweep = bench_onehot_per_chip_sweep(peak)
    sparse_streamed = bench_logreg_sparse_streamed()
    overlap = bench_streamed_overlap_cpu_mesh()
    kmeans = bench_kmeans(peak_bw)
    mlp = bench_mlp_forward(peak)
    mlp_train = bench_mlp_train(peak)
    attention = bench_attention(peak)
    attention_train = bench_attention_train(peak)
    serving = bench_serving()
    open_loop = bench_serving_open_loop()
    tracing = bench_tracing_overhead()
    journal = bench_journal_overhead()
    mlp_serving = bench_mlp_serving_throughput()
    continuous_loop = bench_continuous_loop()
    batch_transform = bench_pipeline_batch_transform()
    fusion = bench_fusion_sweep()
    sharded = bench_sharded_fanout()
    cold_start = bench_cold_start()
    sparse_pipelines = bench_sparse_pipelines()
    precision = bench_precision_sweep()

    detail = {
        "device_kind": kind,
        "peak_bf16_flops": peak,
        "peak_hbm_gbps": peak_bw,
        "workloads": [
            logreg, sparse, sweep, sparse_streamed, overlap, kmeans, mlp,
            mlp_train, attention, attention_train, serving, open_loop,
            tracing, journal, mlp_serving, continuous_loop, batch_transform,
            fusion, sharded, cold_start, sparse_pipelines, precision,
        ],
    }
    with open("BENCH_DETAIL.json", "w") as f:
        json.dump(detail, f, indent=2)

    print(
        json.dumps(
            {
                "metric": "logreg_steady_train_rows_per_sec_d256",
                "value": logreg["steady_rows_per_sec"],
                "unit": "rows/s",
                "vs_baseline": logreg["vs_cpu_baseline"],
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    if "--sharded-child" in sys.argv[1:]:
        sys.exit(_sharded_child())
    if "retrieval_topk" in sys.argv[1:]:
        print(json.dumps(bench_retrieval_topk(), indent=2))
        sys.exit(0)
    if "precision_sweep" in sys.argv[1:]:
        print(json.dumps(bench_precision_sweep(), indent=2))
        sys.exit(0)
    if "training_weak_scaling" in sys.argv[1:]:
        print(json.dumps(bench_training_weak_scaling(), indent=2))
        sys.exit(0)
    sys.exit(main())
