"""Benchmark entry point — prints ONE JSON line.

Workload: the reference's flagship benchmark config (`flink-ml-benchmark/src/main/
resources/benchmark-demo.json` "KMeans-1"): KMeans.fit on 10,000 random dense vectors
of dim 10 with default params (k=2, maxIter=20, euclidean). The reference's
illustrative output for this exact config is totalTimeMs=7148 → inputThroughput
≈ 1399 rows/s on a local CPU Flink cluster (flink-ml-benchmark/README.md:86-113);
that is the ``vs_baseline`` denominator.

Methodology: one warm-up fit triggers XLA compilation (the analogue of the reference
paying JVM/job-graph startup inside netRuntime would unfairly charge one-time
compilation to a steady-state metric); the reported number is the median of 3 timed
fits, full pipeline included (host data → device → train → model data back to host).
"""
import json
import sys
import time

import numpy as np


def main() -> None:
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.models.clustering.kmeans import KMeans

    num_rows, dim = 10_000, 10
    rng = np.random.default_rng(2)
    df = DataFrame.from_dict({"features": rng.random((num_rows, dim))})

    def run():
        t0 = time.perf_counter()
        KMeans().set_seed(2).fit(df)
        return time.perf_counter() - t0

    run()  # warm-up: XLA compile
    times = sorted(run() for _ in range(3))
    elapsed = times[1]
    rows_per_sec = num_rows / elapsed

    baseline = 1399.0  # rows/s, reference KMeans-1 demo output
    print(
        json.dumps(
            {
                "metric": "kmeans_fit_throughput_10k_d10_k2",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
