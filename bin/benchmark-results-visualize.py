#!/usr/bin/env python
"""Visualize benchmark results (ref flink-ml-dist benchmark-results-visualize.py).

Reads one or more results JSON files produced by ``bin/benchmark-run
--output-file`` and renders grouped horizontal bars of the chosen metric per
benchmark — multiple files overlay for before/after comparison.

    bin/benchmark-results-visualize.py results_a.json results_b.json \
        --metric inputThroughput --output comparison.png
"""
import argparse
import json
import os
import sys


def load(path):
    with open(path) as f:
        results = json.load(f)
    return {
        r["name"]: r for r in results if isinstance(r, dict) and "name" in r
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("files", nargs="+", help="results JSON files")
    parser.add_argument(
        "--metric",
        default="inputThroughput",
        help="result field to plot (default inputThroughput, rows/s)",
    )
    parser.add_argument("--output", default="benchmark-results.png")
    args = parser.parse_args(argv)

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    # label by basename, falling back to the full path on collision
    # (before/results.json vs after/results.json must not silently merge)
    basenames = [os.path.basename(p) for p in args.files]
    labels = [
        b if basenames.count(b) == 1 else p for b, p in zip(basenames, args.files)
    ]
    runs = {label: load(p) for label, p in zip(labels, args.files)}
    names = sorted({n for r in runs.values() for n in r})
    if not names:
        print("no benchmark entries found", file=sys.stderr)
        return 1

    y = np.arange(len(names), dtype=float)
    height = 0.8 / len(runs)
    fig, ax = plt.subplots(figsize=(9, max(2.5, 0.5 * len(names) + 1)))
    for i, (label, results) in enumerate(runs.items()):
        vals = [float(results.get(n, {}).get(args.metric, 0.0) or 0.0) for n in names]
        bars = ax.barh(y + i * height, vals, height=height, label=label)
        ax.bar_label(bars, fmt="%.0f", padding=2, fontsize=8)
        for n in names:
            if "error" in results.get(n, {}):
                print(f"note: {label}:{n} errored: {results[n]['error']}", file=sys.stderr)

    ax.set_yticks(y + 0.4 - height / 2, names)
    ax.invert_yaxis()
    ax.set_xlabel(args.metric)
    ax.set_title("flink-ml-tpu benchmark results")
    if len(runs) > 1:
        ax.legend()
    fig.tight_layout()
    fig.savefig(args.output, dpi=120)
    print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
